"""Fused device-resident whole-run for the ViT family.

parallel/fused.py gives the CNN family the TPU-first fast path: dataset
resident in HBM, every epoch a ``lax.scan``, the whole run ONE jitted
device call (one compile, one dispatch+sync — the property that beats the
per-step host round trip by ~20x through a high-RTT tunnel, see the
README bench table and `bench_r3_stepstats.log`).  This module is the
same shape for the attention family, built on fused.py's shared epoch and
eval scan skeletons (`_epoch_scan_builder` / `_eval_scan_builder`) — the
permutation, wrap-fill masking, and batch-slicing semantics are shared BY
CONSTRUCTION; only the step body (ViT forward + Adadelta, no BN, no
dropout, no Pallas-flat state) and the whole-run epoch scan live here.
Parity with the per-batch ViT step is pinned by tests/test_fused_vit.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.vit import ViTConfig, vit_forward
from ..ops.adadelta import adadelta_update
from ..ops.loss import nll_loss
from .ddp import TrainState
from .fused import (  # shared staging + scan skeletons
    _epoch_scan_builder,
    _eval_scan_builder,
    device_put_dataset,
)
from .mesh import DATA_AXIS
from ..utils.jax_compat import shard_map

__all__ = ["device_put_dataset", "make_fused_vit_run"]


def make_fused_vit_run(
    mesh: Mesh,
    cfg: ViTConfig,
    train_size: int,
    test_size: int,
    global_batch: int,
    eval_batch: int,
    epochs: int,
    rho: float = 0.9,
    eps: float = 1e-6,
    start_epoch: int = 1,
    pregather: bool = False,
    zero: bool = False,
):
    """Build the whole-run fusion for the ViT.

    Returns ``(run_fn, num_batches)`` with ``run_fn(state, tr_x, tr_y,
    te_x, te_y, shuffle_key, lrs) -> (state, losses[epochs, num_batches,
    n_shards], evals[epochs, 2])`` — the fused.make_fused_run contract
    minus the dropout key (the family has none).  ``state`` is a
    replicated ddp.TrainState over ViT params — or, with ``zero``, a
    ZeRO-1 state (parallel/zero.py: ``make_zero_train_state``) whose
    flat accumulator shards ride the epoch-scan carry exactly like the
    CNN family's fused ZeRO composition (fused.py ``zero=True``).
    """
    n_shards = mesh.shape[DATA_AXIS]
    if zero:
        from .zero import zero_state_spec, zero_update

    def step_fn(state: TrainState, x, y, w, shard, dropout_key, lr):
        def loss_fn(params):
            logp = vit_forward(params, x, cfg)
            return nll_loss(logp, y, w, reduction="mean")

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        if zero:
            params, opt = zero_update(
                state.params, grads, state.opt, lr, n_shards, rho, eps
            )
        else:
            grads = jax.lax.pmean(grads, DATA_AXIS)
            params, opt = adadelta_update(
                state.params, grads, state.opt, lr, rho, eps
            )
        return TrainState(params, opt, state.step + 1), loss

    local_epoch, num_batches = _epoch_scan_builder(
        train_size, global_batch, n_shards, jnp.float32, step_fn,
        pregather=pregather,
    )
    local_eval = _eval_scan_builder(
        test_size, eval_batch, n_shards, jnp.float32,
        lambda params, x: vit_forward(params, x, cfg),
    )

    def local_run(state, tr_x, tr_y, te_x, te_y, shuffle_key, lrs):
        def one_epoch(state, epoch_and_lr):
            epoch, lr = epoch_and_lr
            # The skeleton's dropout_key slot is unused by the ViT body.
            state, losses = local_epoch(
                state, tr_x, tr_y, epoch, shuffle_key, shuffle_key, lr
            )
            totals = local_eval(state.params, te_x, te_y)
            return state, (losses, totals)

        state, (losses, evals) = jax.lax.scan(
            one_epoch, state,
            (jnp.arange(start_epoch, start_epoch + epochs), lrs),
        )
        # all_gather the per-shard loss traces (fully-replicated output —
        # every process reads locally, no chief-only collective).
        gathered = jax.lax.all_gather(losses, DATA_AXIS)  # [shards, E, B]
        return state, jnp.moveaxis(gathered, 0, -1), evals

    state_spec = zero_state_spec() if zero else P()
    sharded = shard_map(
        local_run,
        mesh=mesh,
        in_specs=(state_spec,) + (P(),) * 6,
        out_specs=(state_spec, P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,)), num_batches
