from .sampler import epoch_indices, per_rank_count
from .mesh import make_mesh, data_sharding, replicated_sharding
from .sp import (
    SEQ_AXIS,
    make_sp_eval_step,
    make_sp_mesh,
    make_sp_train_step,
    ring_attention,
)
from .ep import (
    make_ep_eval_step,
    make_ep_train_step,
    moe_mlp_ep,
    shard_ep_state,
)
from .tp_vit import (
    make_vit_tp_eval_step,
    make_vit_tp_train_step,
    shard_vit_tp_state,
)
from .sp3 import (
    make_3d_mesh,
    make_sp3_eval_step,
    make_sp3_train_step,
    shard_sp3_state,
)
from .pp_vit import (
    make_vit_eval_step,
    make_vit_pp_train_step,
)
from .zero import (
    ZeroAdadeltaState,
    make_zero_train_state,
    make_zero_train_step,
    shard_zero_state,
    zero_opt_to_per_leaf,
)
from .distributed import (
    DistState,
    init_distributed_mode,
    initialize_with_retry,
)
from .elastic import EXIT_GANG, GangSupervisor, RankHeartbeat
from .ddp import (
    TrainState,
    eval_variables,
    make_eval_step,
    make_train_state,
    make_train_step,
    replicate_params,
)
