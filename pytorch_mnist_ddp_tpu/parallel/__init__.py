from .sampler import epoch_indices, per_rank_count
from .mesh import make_mesh, data_sharding, replicated_sharding
from .distributed import init_distributed_mode, DistState
from .ddp import make_train_step, make_eval_step, replicate_params
