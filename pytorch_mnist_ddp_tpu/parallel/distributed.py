"""World formation from the environment (replaces ``init_distributed_mode``,
reference mnist_ddp.py:13-37; SURVEY.md N1/N4).

The reference's contract, preserved here:

- ``RANK`` / ``WORLD_SIZE`` / ``LOCAL_RANK`` env vars select distributed
  mode (mnist_ddp.py:16-19); ``SLURM_PROCID`` is the fallback
  (mnist_ddp.py:20-22); with neither, the script prints
  "Not using distributed mode" and degrades to single-device
  (mnist_ddp.py:25-28).
- ``MASTER_ADDR``/``MASTER_PORT`` (the ``env://`` init method,
  mnist_ddp.py:134) provide the rendezvous address.

The JAX mapping differs in one structural way: a torch process drives ONE
GPU, while a JAX process drives EVERY local chip (SPMD).  So:

- ``RANK``/``WORLD_SIZE`` count *processes* (= hosts); multi-host world
  formation is ``jax.distributed.initialize`` (the DCN rendezvous that
  replaces TCPStore+NCCL bootstrap).
- The launcher's ``--nproc_per_node=N`` (reference README.md:42) maps to
  "N local devices in one process" and is conveyed by ``NPROC_PER_NODE``
  (see ``parallel/launch.py``).
- The *data-parallel world size* (the reference's GPU count, used for the
  global sample counter at mnist_ddp.py:78) is the total device count.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import jax

from ..utils.logging import NOT_DISTRIBUTED_NOTICE, distributed_init_banner


@dataclass
class DistState:
    """Resolved distributed topology for this process."""

    distributed: bool = False
    process_rank: int = 0      # sampler-sharding rank (one shard per host)
    process_count: int = 1
    world_size: int = 1        # total devices = data-parallel degree
    local_rank: int = 0
    devices: list = field(default_factory=list)
    dist_url: str = "env://"
    # Rendezvous attempts it took to form this world (0 = no rendezvous
    # ran); fit() exports it as rendezvous_attempts_total.
    rendezvous_attempts: int = 0

    @property
    def is_chief(self) -> bool:
        """Rank-0 gate for logging/eval/checkpointing (mnist_ddp.py:75)."""
        return self.process_rank == 0

    @property
    def local_device_count(self) -> int:
        return len(self.devices)


def _coordinator_address(dist_url: str) -> str | None:
    if dist_url and dist_url != "env://":
        return dist_url.removeprefix("tcp://")
    addr = os.environ.get("MASTER_ADDR")
    port = os.environ.get("MASTER_PORT")
    if addr and port:
        return f"{addr}:{port}"
    if addr or port:
        # Satellite fix (ISSUE 10): half an env:// address used to fall
        # through to coordinator_address=None — jax then guesses or the
        # rendezvous hangs, neither of which names the operator's actual
        # mistake.  One pointed error, naming the MISSING variable.
        missing = "MASTER_PORT" if addr else "MASTER_ADDR"
        present = "MASTER_ADDR" if addr else "MASTER_PORT"
        raise ValueError(
            f"{present} is set but {missing} is not: the env:// rendezvous "
            f"needs both — export {missing} (the launcher sets the pair "
            "from --master_addr/--master_port)"
        )
    return None


def initialize_with_retry(
    coordinator_address: str | None,
    num_processes: int,
    process_id: int,
    timeout_s: float = 60.0,
    attempts: int = 2,
    backoff_s: float = 1.0,
    initialize_fn=None,
    sink=None,
) -> int:
    """``jax.distributed.initialize`` under a BOUNDED total budget:
    ``attempts`` tries splitting ``timeout_s`` between them (so the call
    fails within the budget regardless of the attempt count), retry
    backoff between tries, and a pointed who-is-missing diagnostic
    instead of jax's default 300-second near-hang.

    Returns the number of attempts used.  ``initialize_fn`` and
    ``sink`` are injectable for tests (a fake initializer / an event
    sink receiving ``rendezvous_retry`` + final ``rendezvous`` events).
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    # Probe the coordinator socket before the real initialize on
    # non-coordinator ranks: jax's distributed client LOG(FATAL)s the
    # whole process when the coordinator never answers (client.h
    # "Terminating process...") — un-catchable, un-diagnosable.  A
    # bounded TCP probe turns "coordinator absent" into a Python
    # exception the retry ladder and the pointed terminal error can
    # own.  Injected initializers (tests) skip it.
    probe = initialize_fn is None and process_id != 0
    if initialize_fn is None:
        initialize_fn = jax.distributed.initialize
    # Per-attempt timeout: the TOTAL rendezvous budget is timeout_s (the
    # --rdzv-timeout-s contract — "fails within", not "times that").
    # Every leg — probe, initialize, backoff — is clamped against one
    # shared deadline, so probe+initialize cannot stack to 2x the
    # budget and backoffs cannot extend past it (worst-case slop is
    # the ~1 s minimum window each leg is guaranteed).
    per_attempt = max(1, int(timeout_s / attempts))
    deadline = time.monotonic() + float(timeout_s)

    def _window() -> int:
        return max(1, min(per_attempt, int(deadline - time.monotonic())))

    last_err: Exception | None = None
    for attempt in range(1, attempts + 1):
        if attempt > 1 and time.monotonic() >= deadline:
            break  # budget spent; fail now with the terminal diagnostic
        try:
            if probe and coordinator_address:
                _await_coordinator(coordinator_address, _window())
            initialize_fn(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=_window(),
            )
            if sink is not None:
                sink.emit(
                    "rendezvous",
                    attempts=attempt,
                    ok=True,
                    coordinator=coordinator_address,
                    rank=process_id,
                    world=num_processes,
                )
            return attempt
        except Exception as e:  # jax raises RuntimeError/XlaRuntimeError
            last_err = e
            # A failed attempt can leave the client half-initialized;
            # tear it down so the retry starts clean.
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            if attempt < attempts:
                if sink is not None:
                    sink.emit(
                        "rendezvous_retry",
                        attempt=attempt,
                        timeout_s=per_attempt,
                        coordinator=coordinator_address,
                        error=f"{type(e).__name__}: {e}",
                    )
                time.sleep(
                    min(
                        backoff_s * (2 ** (attempt - 1)),
                        max(0.0, deadline - time.monotonic()),
                    )
                )
    if sink is not None:
        sink.emit(
            "rendezvous",
            attempts=attempts,
            ok=False,
            coordinator=coordinator_address,
            rank=process_id,
            world=num_processes,
        )
    raise RuntimeError(
        f"rendezvous at {coordinator_address!r} failed after {attempts} "
        f"attempt(s) x {per_attempt}s (budget {timeout_s:g}s) as process "
        f"{process_id} of {num_processes}: a peer never arrived — check "
        f"that every rank 0..{num_processes - 1} is running and that "
        "MASTER_ADDR/MASTER_PORT match on every host "
        f"(last error: {type(last_err).__name__}: {last_err})"
    ) from last_err


def _await_coordinator(coordinator_address: str, timeout_s: float) -> None:
    """Wait (bounded) for the coordinator's TCP socket to accept; raise
    a catchable ConnectionError when it never does within the window."""
    import socket

    host, _, port = coordinator_address.rpartition(":")
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            with socket.create_connection((host, int(port)), timeout=1.0):
                return
        except OSError as e:
            if time.monotonic() >= deadline:
                raise ConnectionError(
                    f"coordinator {coordinator_address} not accepting "
                    f"connections within {timeout_s:g}s"
                ) from e
            time.sleep(0.2)


def _enable_cpu_collectives() -> None:
    """Select the gloo cross-process collectives implementation for the
    CPU client.  The pinned jaxlib SHIPS gloo but defaults to 'none',
    so a multi-rank CPU gang formed without this dies at its first
    psum with "Multiprocess computations aren't implemented on the CPU
    backend" — after a clean-looking rendezvous.  Must run before the
    backend initializes (same ordering constraint as the rendezvous
    itself); harmless on accelerator platforms (it only parameterizes
    CPU client creation) and on jax builds without the option."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def _distributed_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists (newer jax);
    the pinned 0.4.x image predates it, so fall back to probing the
    distributed client state directly — the old direct call raised
    AttributeError on EVERY multi-process launch here."""
    checker = getattr(jax.distributed, "is_initialized", None)
    if checker is not None:
        return bool(checker())
    try:
        from jax._src import distributed as _dist_src

        return _dist_src.global_state.client is not None
    except Exception:
        return False


def _rendezvous_sink(process_rank: int):
    """Per-rank JSONL sink for rendezvous events when the launcher (or
    an operator) exported ``ELASTIC_TELEMETRY_DIR`` — the retry trail
    must land somewhere BEFORE the trainer's telemetry exists, since
    world formation is the first thing a rank does."""
    directory = os.environ.get("ELASTIC_TELEMETRY_DIR")
    if not directory:
        return None
    from ..obs.events import EventSink

    return EventSink(
        directory,
        rank=process_rank,
        filename=f"events-rdzv-rank{process_rank}.jsonl",
    )


def init_distributed_mode(
    dist_url: str = "env://",
    devices_per_process: int | None = None,
    quiet: bool = False,
    rdzv_timeout_s: float | None = None,
    rdzv_attempts: int | None = None,
) -> DistState:
    """Resolve the world from the environment, mirroring the reference's
    decision tree (mnist_ddp.py:13-37), and return a ``DistState``.

    ``devices_per_process`` caps how many local devices join the mesh
    (the ``--nproc_per_node`` request); ``None`` uses all of them.

    ``rdzv_timeout_s``/``rdzv_attempts`` bound the rendezvous
    (:func:`initialize_with_retry`); ``None`` reads the launcher's
    ``RDZV_TIMEOUT_S``/``RDZV_ATTEMPTS`` env contract, falling back to
    60 s total over 2 attempts — never the indefinite-looking jax
    default.
    """
    env = os.environ
    # --nproc_per_node caps local devices in every mode (the launcher sets
    # NPROC_PER_NODE for both single- and multi-node runs).
    if devices_per_process is None and "NPROC_PER_NODE" in env:
        devices_per_process = int(env["NPROC_PER_NODE"])
    if "RANK" in env and "WORLD_SIZE" in env:
        process_rank = int(env["RANK"])
        process_count = int(env["WORLD_SIZE"])
        local_rank = int(env.get("LOCAL_RANK", 0))
    elif "SLURM_PROCID" in env:
        process_rank = int(env["SLURM_PROCID"])
        process_count = int(env.get("SLURM_NTASKS", 1))
        local_rank = 0
    elif devices_per_process is not None:
        # Single-host SPMD: one process drives N local devices.
        process_rank, process_count, local_rank = 0, 1, 0
    else:
        if not quiet:
            print(NOT_DISTRIBUTED_NOTICE)
        return DistState(devices=jax.local_devices()[:1], dist_url=dist_url)

    rendezvous_attempts = 0
    if process_count > 1 and not _distributed_initialized():
        # Multi-host rendezvous (replaces TCPStore + NCCL bootstrap).
        # NOTE: must run before anything touches the XLA backend — even
        # jax.process_count() would initialize it and make this raise.
        _enable_cpu_collectives()
        if rdzv_timeout_s is None:
            rdzv_timeout_s = float(env.get("RDZV_TIMEOUT_S", 60.0))
        if rdzv_attempts is None:
            rdzv_attempts = int(env.get("RDZV_ATTEMPTS", 2))
        sink = _rendezvous_sink(process_rank)
        try:
            rendezvous_attempts = initialize_with_retry(
                _coordinator_address(dist_url),
                process_count,
                process_rank,
                timeout_s=rdzv_timeout_s,
                attempts=rdzv_attempts,
                sink=sink,
            )
        finally:
            if sink is not None:
                sink.close()

    local = jax.local_devices()
    if devices_per_process is not None:
        if devices_per_process > len(local):
            raise RuntimeError(
                f"--nproc_per_node={devices_per_process} requested but only "
                f"{len(local)} local device(s) are available"
            )
        local = local[:devices_per_process]

    world_size = len(local) * process_count
    state = DistState(
        distributed=True,
        process_rank=process_rank,
        process_count=process_count,
        world_size=world_size,
        local_rank=local_rank,
        devices=local,
        dist_url=dist_url,
        rendezvous_attempts=rendezvous_attempts,
    )
    if not quiet:
        print(
            distributed_init_banner(
                state.process_rank, dist_url, state.local_rank, state.world_size
            ),
            flush=True,
        )
    return state
