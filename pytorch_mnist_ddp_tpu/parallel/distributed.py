"""World formation from the environment (replaces ``init_distributed_mode``,
reference mnist_ddp.py:13-37; SURVEY.md N1/N4).

The reference's contract, preserved here:

- ``RANK`` / ``WORLD_SIZE`` / ``LOCAL_RANK`` env vars select distributed
  mode (mnist_ddp.py:16-19); ``SLURM_PROCID`` is the fallback
  (mnist_ddp.py:20-22); with neither, the script prints
  "Not using distributed mode" and degrades to single-device
  (mnist_ddp.py:25-28).
- ``MASTER_ADDR``/``MASTER_PORT`` (the ``env://`` init method,
  mnist_ddp.py:134) provide the rendezvous address.

The JAX mapping differs in one structural way: a torch process drives ONE
GPU, while a JAX process drives EVERY local chip (SPMD).  So:

- ``RANK``/``WORLD_SIZE`` count *processes* (= hosts); multi-host world
  formation is ``jax.distributed.initialize`` (the DCN rendezvous that
  replaces TCPStore+NCCL bootstrap).
- The launcher's ``--nproc_per_node=N`` (reference README.md:42) maps to
  "N local devices in one process" and is conveyed by ``NPROC_PER_NODE``
  (see ``parallel/launch.py``).
- The *data-parallel world size* (the reference's GPU count, used for the
  global sample counter at mnist_ddp.py:78) is the total device count.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import jax

from ..utils.logging import NOT_DISTRIBUTED_NOTICE, distributed_init_banner


@dataclass
class DistState:
    """Resolved distributed topology for this process."""

    distributed: bool = False
    process_rank: int = 0      # sampler-sharding rank (one shard per host)
    process_count: int = 1
    world_size: int = 1        # total devices = data-parallel degree
    local_rank: int = 0
    devices: list = field(default_factory=list)
    dist_url: str = "env://"

    @property
    def is_chief(self) -> bool:
        """Rank-0 gate for logging/eval/checkpointing (mnist_ddp.py:75)."""
        return self.process_rank == 0

    @property
    def local_device_count(self) -> int:
        return len(self.devices)


def _coordinator_address(dist_url: str) -> str | None:
    if dist_url and dist_url != "env://":
        return dist_url.removeprefix("tcp://")
    addr = os.environ.get("MASTER_ADDR")
    port = os.environ.get("MASTER_PORT")
    if addr and port:
        return f"{addr}:{port}"
    return None


def init_distributed_mode(
    dist_url: str = "env://",
    devices_per_process: int | None = None,
    quiet: bool = False,
) -> DistState:
    """Resolve the world from the environment, mirroring the reference's
    decision tree (mnist_ddp.py:13-37), and return a ``DistState``.

    ``devices_per_process`` caps how many local devices join the mesh
    (the ``--nproc_per_node`` request); ``None`` uses all of them.
    """
    env = os.environ
    # --nproc_per_node caps local devices in every mode (the launcher sets
    # NPROC_PER_NODE for both single- and multi-node runs).
    if devices_per_process is None and "NPROC_PER_NODE" in env:
        devices_per_process = int(env["NPROC_PER_NODE"])
    if "RANK" in env and "WORLD_SIZE" in env:
        process_rank = int(env["RANK"])
        process_count = int(env["WORLD_SIZE"])
        local_rank = int(env.get("LOCAL_RANK", 0))
    elif "SLURM_PROCID" in env:
        process_rank = int(env["SLURM_PROCID"])
        process_count = int(env.get("SLURM_NTASKS", 1))
        local_rank = 0
    elif devices_per_process is not None:
        # Single-host SPMD: one process drives N local devices.
        process_rank, process_count, local_rank = 0, 1, 0
    else:
        if not quiet:
            print(NOT_DISTRIBUTED_NOTICE)
        return DistState(devices=jax.local_devices()[:1], dist_url=dist_url)

    if process_count > 1 and not jax.distributed.is_initialized():
        # Multi-host rendezvous (replaces TCPStore + NCCL bootstrap).
        # NOTE: must run before anything touches the XLA backend — even
        # jax.process_count() would initialize it and make this raise.
        jax.distributed.initialize(
            coordinator_address=_coordinator_address(dist_url),
            num_processes=process_count,
            process_id=process_rank,
        )

    local = jax.local_devices()
    if devices_per_process is not None:
        if devices_per_process > len(local):
            raise RuntimeError(
                f"--nproc_per_node={devices_per_process} requested but only "
                f"{len(local)} local device(s) are available"
            )
        local = local[:devices_per_process]

    world_size = len(local) * process_count
    state = DistState(
        distributed=True,
        process_rank=process_rank,
        process_count=process_count,
        world_size=world_size,
        local_rank=local_rank,
        devices=local,
        dist_url=dist_url,
    )
    if not quiet:
        print(
            distributed_init_banner(
                state.process_rank, dist_url, state.local_rank, state.world_size
            ),
            flush=True,
        )
    return state
