"""ZeRO-1 data parallelism: Adadelta state sharded 1/N over the data axis.

Plain DP (parallel/ddp.py) replicates the optimizer state and has every
replica redundantly apply the identical update — the reference's DDP
semantics (its allreduce at reference mnist_ddp.py:172-174 synchronizes
gradients; ``optim.Adadelta`` state is per-rank-replicated).  The ZeRO
family of optimizations (Rajbhandari et al., stage 1) removes that
redundancy: each of the N data shards owns 1/N of the optimizer state and
updates only its slice.  The TPU-native formulation replaces
"reduce-scatter + per-rank optimizer + all-gather over NCCL" with three
XLA collectives inside ONE jitted shard_map step:

    grads  --psum_scatter-->  mean-gradient shard        (rides ICI)
    shard Adadelta update on the local 1/N flat slice    (VPU, no comm)
    delta  --all_gather--->   full update, applied to the replicated params

Per step this moves exactly the same bytes as plain DP's gradient pmean
(a pmean IS reduce-scatter + all-gather on ring topologies) while storing
``2 * P / N`` optimizer floats per chip instead of ``2 * P`` — the win
that matters when the optimizer state, not the params, bounds model size
per chip (Adadelta/Adam carry 2x params).  At MNIST scale the saving is
cosmetic; the point is the framework shape: the same step works unchanged
at any P and N.

The accumulators live in ONE flat padded f32 vector per buffer (global
shape ``[chunk * N]``, sharded ``P('data')``), not per-leaf pytrees —
sharding every leaf 1/N would splinter small tensors below tile
granularity, whereas one vector scatters into N contiguous lane-aligned
chunks.  The layout is the 1-D cousin of the Pallas kernel's persistent
flat state (ops/pallas_adadelta.py:FlatAdadeltaState) and converts
losslessly to the per-leaf layout for checkpoints
(:func:`zero_opt_to_per_leaf` / :func:`per_leaf_opt_to_zero_host`), so
``--save-state`` archives stay portable across ``--zero`` and plain runs.

Numerics: the update math is ops/adadelta.py's exact torch recurrence on
a mean gradient; only the reduction routing differs (psum_scatter vs
pmean — same adder trees on the same axis).  The dropout streams reuse
``ddp.fold_replica_step_key``, so a ZeRO-1 trajectory is directly
comparable to plain DP's even with dropout on (tests/test_zero.py pins
both to near-bitwise agreement).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.net import Net
from ..ops.adadelta import AdadeltaState, adadelta_delta
from .ddp import TrainState, forward_loss, fold_replica_step_key
from .mesh import DATA_AXIS, place_tree
from ..utils.jax_compat import shard_map


class ZeroAdadeltaState(NamedTuple):
    """Adadelta accumulators as flat padded f32 vectors, global shape
    ``[chunk * num_shards]`` sharded ``P('data')`` — each data shard owns
    one contiguous ``chunk``-length slice.  A DISTINCT type (like
    ``FlatAdadeltaState``): layout dispatch keys on ``isinstance``, never
    on array shape."""

    square_avg: jax.Array
    acc_delta: jax.Array


def zero_chunk(n_params: int, n_shards: int) -> int:
    """Per-shard slice length: the padded flat vector divides exactly."""
    return -(-n_params // n_shards)


def _flatten_grads(grads: Any, n_shards: int):
    """Ravel a gradient pytree and zero-pad to ``chunk * n_shards``."""
    flat, unravel = ravel_pytree(grads)
    n = flat.shape[0]
    chunk = zero_chunk(n, n_shards)
    return jnp.pad(flat, (0, chunk * n_shards - n)), n, unravel


def zero_opt_spec() -> ZeroAdadeltaState:
    """The accumulators' PartitionSpecs (pytree-of-specs form)."""
    return ZeroAdadeltaState(square_avg=P(DATA_AXIS), acc_delta=P(DATA_AXIS))


def zero_state_spec(batch_stats_spec=P()) -> TrainState:
    """PartitionSpecs for a whole ZeRO-1 ``TrainState``: params/step/BN
    replicated, optimizer sharded over the data axis."""
    return TrainState(
        params=P(), opt=zero_opt_spec(), step=P(), batch_stats=batch_stats_spec
    )


def zero_init(params: Any, mesh: Mesh) -> ZeroAdadeltaState:
    """Zero-valued sharded accumulators for ``params`` on ``mesh``.

    Built inside ``jit`` with explicit ``out_shardings`` so the zeros are
    created directly in place on every shard — correct in multi-controller
    worlds too (all processes enqueue the same program; no host broadcast).
    """
    n = sum(int(p.size) for p in jax.tree.leaves(params))
    total = zero_chunk(n, mesh.shape[DATA_AXIS]) * mesh.shape[DATA_AXIS]
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    make = jax.jit(
        lambda: ZeroAdadeltaState(
            square_avg=jnp.zeros(total, jnp.float32),
            acc_delta=jnp.zeros(total, jnp.float32),
        ),
        out_shardings=ZeroAdadeltaState(square_avg=sharding, acc_delta=sharding),
    )
    return make()


def zero_opt_to_per_leaf(
    opt: ZeroAdadeltaState, params: Any, mesh: Mesh
) -> AdadeltaState:
    """Gather + unravel the sharded flat accumulators into the per-leaf
    pytree layout (checkpoint portability: ``--save-state`` archives are
    always written per-leaf, whatever the run executed).

    The gather is a jitted all-replicate enqueued on EVERY process (a
    chief-only collective would deadlock a multi-controller world; the
    file write alone is chief-gated, trainer.py), so afterwards each
    process holds the full accumulators locally."""
    replicated = jax.jit(
        lambda v: v, out_shardings=NamedSharding(mesh, P())
    )(opt)
    flat_p, unravel = ravel_pytree(params)
    n = flat_p.shape[0]
    return AdadeltaState(
        square_avg=unravel(replicated.square_avg[:n]),
        acc_delta=unravel(replicated.acc_delta[:n]),
    )


def per_leaf_opt_to_zero_host(opt: AdadeltaState, n_shards: int):
    """Host-side per-leaf → flat-padded conversion (resume path).  Returns
    a host ``ZeroAdadeltaState``-shaped tuple of np arrays, ready for
    :func:`shard_zero_state` placement."""
    flat_sq, _ = ravel_pytree(opt.square_avg)
    flat_ac, _ = ravel_pytree(opt.acc_delta)
    n = flat_sq.shape[0]
    pad = zero_chunk(n, n_shards) * n_shards - n
    topad = lambda v: np.pad(np.asarray(v), (0, pad))
    return ZeroAdadeltaState(
        square_avg=topad(flat_sq), acc_delta=topad(flat_ac)
    )


def make_zero_train_state(
    params: Any, mesh: Mesh, batch_stats: Any = (), step0: int = 0
):
    """Fresh ZeRO-1 training state: replicated params/step/BN stats,
    sharded zero accumulators.  ``step0`` seeds the step counter (the
    ``--resume`` cumulative-batch continuation, trainer.py)."""
    from .ddp import replicate_params

    placed = replicate_params(
        TrainState(params=params, opt=(), step=np.int32(step0),
                   batch_stats=batch_stats),
        mesh,
    )
    return placed._replace(opt=zero_init(params, mesh))


def shard_zero_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place a HOST per-leaf ``TrainState`` (e.g. a ``--resume-state``
    archive) as a ZeRO-1 state: params/step/BN replicated, accumulators
    converted to the flat sharded layout.  Multi-controller-safe via
    ``mesh.place_tree``."""
    n_shards = mesh.shape[DATA_AXIS]
    host = state._replace(opt=per_leaf_opt_to_zero_host(state.opt, n_shards))
    # place_tree maps specs leaf-for-leaf (no pytree-prefix broadcast, unlike
    # shard_map's in_specs), so expand the replicated positions per leaf.
    specs = host._replace(
        params=jax.tree.map(lambda _: P(), host.params),
        opt=zero_opt_spec(),
        step=P(),
        batch_stats=jax.tree.map(lambda _: P(), host.batch_stats),
    )
    return place_tree(host, specs, mesh)


def zero_update(
    params: Any,
    grads: Any,
    opt: ZeroAdadeltaState,
    lr,
    n_shards: int,
    rho: float = 0.9,
    eps: float = 1e-6,
) -> tuple[Any, ZeroAdadeltaState]:
    """The model-agnostic ZeRO-1 optimizer core.  MUST be called inside a
    ``shard_map`` over a mesh whose data axis has ``n_shards`` members,
    with ``grads`` the LOCAL per-shard gradients and ``opt`` the local
    accumulator slices.

    Three moves: (1) reduce-scatter — this shard's slice of the MEAN
    gradient (the pmean's first half; the sum lands here, the /N makes it
    DDP's mean); (2) the shared torch Adadelta recurrence
    (ops/adadelta.py:adadelta_delta) on the local 1/N flat slice — pure
    VPU work XLA fuses into the collectives around it; (3) all-gather the
    full delta (the pmean's second half) and fold ``p - lr*delta`` into
    each leaf at the unravel split, so params themselves never ravel (the
    Pallas flat-state lesson, ops/pallas_adadelta.py).  Shared by the CNN
    step below and the ViT step (:func:`make_zero_vit_train_step`)."""
    g_pad, n, unravel = _flatten_grads(grads, n_shards)
    g_shard = jax.lax.psum_scatter(g_pad, DATA_AXIS, tiled=True) / n_shards
    delta_shard, sq, ac = adadelta_delta(
        g_shard, opt.square_avg, opt.acc_delta, rho, eps
    )
    delta = unravel(
        jax.lax.all_gather(delta_shard, DATA_AXIS, tiled=True)[:n]
    )
    new_params = jax.tree.map(lambda p, d: p - lr * d, params, delta)
    return new_params, ZeroAdadeltaState(square_avg=sq, acc_delta=ac)


def make_zero_train_step(
    mesh: Mesh,
    compute_dtype: jnp.dtype = jnp.float32,
    rho: float = 0.9,
    eps: float = 1e-6,
    dropout: bool = True,
    use_bn: bool = False,
    conv_impl: str = "conv",
):
    """Build the jitted ZeRO-1 DP train step.

    Same signature and semantics as ``ddp.make_train_step`` —
    ``step_fn(state, x, y, w, dropout_key, lr) -> (state, losses)`` — with
    ``state.opt`` a :class:`ZeroAdadeltaState`.  The returned per-replica
    local losses and the trained params match plain DP's (the recurrence
    is identical; only where the accumulators LIVE differs).
    """
    n_shards = mesh.shape[DATA_AXIS]
    model = Net(
        compute_dtype=compute_dtype, use_bn=use_bn,
        bn_axis=DATA_AXIS if use_bn else None, conv_impl=conv_impl,
    )

    def local_step(state: TrainState, x, y, w, dropout_key, lr):
        key = fold_replica_step_key(dropout_key, state.step)

        def loss_fn(params):
            return forward_loss(
                model, params, state.batch_stats, x, y, w, key,
                use_bn=use_bn, dropout=dropout,
            )

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        params, opt = zero_update(
            state.params, grads, state.opt, lr, n_shards, rho, eps
        )
        new_state = TrainState(
            params=params, opt=opt, step=state.step + 1,
            batch_stats=new_stats,
        )
        return new_state, loss[None]  # keep a per-shard loss axis

    state_spec = zero_state_spec()
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=(state_spec, P(DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_zero_vit_train_step(mesh: Mesh, cfg, rho: float = 0.9,
                             eps: float = 1e-6, attention_fn=None):
    """ZeRO-1 data-parallel train step for the ViT family
    (``vit_mnist.py --zero``) — the same :func:`zero_update` core under a
    different model's loss.  Signature matches the family's other steps:
    ``step_fn(state, x, y, w, lr) -> (state, losses)`` (the ViT has no
    dropout, so no key threads through).  Eval reuses the family's shared
    DP eval (parallel/pp_vit.py:make_vit_eval_step — params replicated)."""
    from ..models.vit import vit_forward
    from ..ops.attention import full_attention
    from ..ops.loss import nll_loss

    if attention_fn is None:
        attention_fn = full_attention
    n_shards = mesh.shape[DATA_AXIS]

    def local_step(state: TrainState, x, y, w, lr):
        def loss_fn(p):
            return nll_loss(
                vit_forward(p, x, cfg, attention_fn=attention_fn),
                y, w, reduction="mean",
            )

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        params, opt = zero_update(
            state.params, grads, state.opt, lr, n_shards, rho, eps
        )
        new_state = TrainState(
            params=params, opt=opt, step=state.step + 1,
            batch_stats=state.batch_stats,
        )
        return new_state, loss[None]

    state_spec = zero_state_spec()
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(state_spec, P(DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))
