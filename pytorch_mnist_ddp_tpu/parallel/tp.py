"""Tensor parallelism over the mesh's ``model`` axis (SURVEY.md §2c).

The reference is data-parallel only, but its README points at DDP's
model-parallel story (reference README.md:8) and SURVEY.md §2c directs the
mesh design to "leave a ``model`` axis possible".  This module makes that
axis REAL: a 2-D ``(data, model)`` train step where the classifier MLP is
Megatron-style tensor-parallel —

- **fc1 column-parallel**: kernel ``[9216, 128]`` split over ``model`` →
  each shard computes its 128/M output features locally; relu and dropout
  are feature-elementwise, so no communication.
- **fc2 row-parallel**: kernel ``[128, 10]`` split along its input dim →
  each shard holds a partial logit sum; ONE ``psum`` over ``model``
  completes the logits (the only TP collective in the forward).
- convs stay replicated (they are 0.03% of the params; sharding them would
  trade one broadcast for no win at this scale).

Gradients reverse the same pattern under ``jax.grad`` automatically
(``psum`` transposes to identity on the partial-sum path, and the sharded
params' grads stay sharded), then data-parallel ``pmean`` over ``data``
runs per-shard — gradient traffic is 1/M of pure DP for the sharded
layers.  The Adadelta update runs on local shards (elementwise, so sharded
state is exact).

Forward math, init, loss, and update are the same functions the DP path
uses (models/net.py semantics; ops/adadelta.py) — parity is pinned by
tests/test_tp.py against the single-device step.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.net import DROPOUT1_RATE, DROPOUT2_RATE, raw_conv_stack
from ..ops.adadelta import AdadeltaState, adadelta_update
from ..ops.loss import nll_loss
from .ddp import TrainState
from .mesh import DATA_AXIS, MODEL_AXIS, place_tree
from ..utils.jax_compat import shard_map


def param_specs() -> dict:
    """PartitionSpecs for the Net param tree under (data, model) sharding:
    convs replicated, fc1 column-parallel, fc2 row-parallel."""
    return {
        "conv1": {"kernel": P(), "bias": P()},
        "conv2": {"kernel": P(), "bias": P()},
        "fc1": {"kernel": P(None, MODEL_AXIS), "bias": P(MODEL_AXIS)},
        "fc2": {"kernel": P(MODEL_AXIS, None), "bias": P()},
    }


def state_specs() -> Any:
    """Specs for the full TrainState (params + both Adadelta accumulators +
    step counter): accumulators shard exactly like their params."""
    ps = param_specs()
    return TrainState(
        params=ps, opt=AdadeltaState(square_avg=ps, acc_delta=ps), step=P()
    )


def shard_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place a (host/replicated) TrainState onto the 2-D mesh with TP
    shardings (mesh.place_tree recipe: device_put single-controller,
    per-shard make_array_from_callback multi-controller)."""
    return place_tree(state, state_specs(), mesh)


def _tp_forward(
    params: dict, x: jax.Array, train: bool, key: jax.Array,
    compute_dtype: jnp.dtype = jnp.float32,
) -> jax.Array:
    """The reference CNN forward (models/net.py architecture) written over
    raw params so the dense layers can be local shards.  ``x`` is the
    data-shard batch [n, 28, 28, 1]; fc1/fc2 params are model shards.
    ``compute_dtype`` mirrors ``Net.compute_dtype`` — with bf16 the
    model-axis logits psum moves half the bytes, and the log_softmax tail
    stays f32 exactly like the DP model's."""
    x = raw_conv_stack(params, x, compute_dtype)
    if train:
        keep1 = 1.0 - DROPOUT1_RATE
        k1 = jax.random.fold_in(key, 1)
        x = x * jax.random.bernoulli(k1, keep1, x.shape) / keep1
    x = x.reshape(x.shape[0], -1)  # [n, 9216] NHWC flatten order

    # Column-parallel fc1: local [9216, 128/M] shard -> local features.
    h = x @ params["fc1"]["kernel"].astype(compute_dtype) \
        + params["fc1"]["bias"].astype(compute_dtype)
    h = jax.nn.relu(h)
    if train:
        # Distinct dropout mask per model shard (its features are distinct).
        keep2 = 1.0 - DROPOUT2_RATE
        k2 = jax.random.fold_in(
            jax.random.fold_in(key, 2), jax.lax.axis_index(MODEL_AXIS)
        )
        h = h * jax.random.bernoulli(k2, keep2, h.shape) / keep2
    # Row-parallel fc2: partial logits, completed by one psum over model.
    logits = h @ params["fc2"]["kernel"].astype(compute_dtype)
    logits = jax.lax.psum(logits, MODEL_AXIS) \
        + params["fc2"]["bias"].astype(compute_dtype)
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)


def gather_replicated(tree: Any, mesh: Mesh) -> Any:
    """All-gather a (possibly model-sharded) pytree to a fully-replicated
    copy every process can read locally (``np.asarray`` on each leaf).

    This is a COLLECTIVE: call it on every process of a multi-controller
    world, never behind a chief-only gate."""
    return jax.jit(lambda t: t, out_shardings=NamedSharding(mesh, P()))(tree)


def make_tp_eval_step(mesh: Mesh, compute_dtype: jnp.dtype = jnp.float32):
    """Build the jitted TP eval step: the TP forward (logits completed by
    the model-axis psum) feeding the same psum'd (loss_sum, correct)
    totals as ddp.make_eval_step — so ``--tp`` runs evaluate with
    model-sharded params instead of gathering them every epoch.

    ``eval_fn(params, x, y, w) -> [loss_sum, correct]`` with ``params``
    sharded per ``param_specs()`` and ``x/y/w`` sharded over ``data``."""

    def local_eval(params, x, y, w):
        # train=False: the key argument is never consumed.
        logp = _tp_forward(
            params, x, train=False, key=jax.random.PRNGKey(0),
            compute_dtype=compute_dtype,
        )
        loss_sum = nll_loss(logp, y, w, reduction="sum")
        correct = ((jnp.argmax(logp, axis=1) == y) * w).sum()
        return jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(param_specs(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    )
    return jax.jit(sharded)


def make_tp_predict_step(
    mesh: Mesh, compute_dtype: jnp.dtype = jnp.float32
):
    """Build the jitted TP forward for the serving path: the model-sharded
    twin of ``ddp.make_predict_step``.

    ``predict_fn(params, x) -> log_probs`` with ``params`` sharded per
    ``param_specs()`` and ``x``/the output sharded over ``data`` (size 1
    on a pure-TP serving replica mesh, so every model shard sees the full
    batch).  Same math as the eval step's forward — the fc2 psum is the
    only collective — so parity with the single-device reference is the
    same pin tests/test_tp.py holds for training."""

    def local_predict(params, x):
        return _tp_forward(
            params, x, train=False, key=jax.random.PRNGKey(0),
            compute_dtype=compute_dtype,
        )

    sharded = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(param_specs(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
    )
    return jax.jit(sharded)


def make_tp_train_step(
    mesh: Mesh,
    rho: float = 0.9,
    eps: float = 1e-6,
    dropout: bool = True,
    compute_dtype: jnp.dtype = jnp.float32,
):
    """Build the jitted 2-D (data x model) train step.

    ``step_fn(state, x, y, w, dropout_key, lr) -> (state, losses)`` with
    ``state`` sharded per ``state_specs()`` (see ``shard_state``), ``x``
    sharded over ``data``, and ``losses`` one local loss per data shard.
    """
    num_data = mesh.shape[DATA_AXIS]

    def local_step(state: TrainState, x, y, w, dropout_key, lr):
        key = jax.random.fold_in(dropout_key, state.step)
        key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))

        def loss_fn(params):
            logp = _tp_forward(
                params, x, train=dropout, key=key,
                compute_dtype=compute_dtype,
            )
            return nll_loss(logp, y, w, reduction="mean")

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # This shard_map runs with VMA tracking ON (check_vma default), so
        # AD already psums each param's cotangent over every mesh axis the
        # param is invariant on — the DP allreduce over ``data`` AND the
        # model-axis reduction for replicated (conv) params come out of the
        # transpose itself.  What arrives here is the SUM of per-shard
        # local-mean grads; DDP semantics are the mean, so divide by the
        # data-parallel degree.  (A manual pmean would re-sum the already-
        # reduced value — 4x grads on a 4-way data axis.)
        grads = jax.tree.map(lambda g: g / num_data, grads)
        params, opt = adadelta_update(
            state.params, grads, state.opt, lr, rho, eps
        )
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_specs(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=(state_specs(), P(DATA_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))
