"""Pipeline parallelism for the ViT family: transformer blocks as stages.

The textbook transformer pipeline — depth splits across S stages
(``--pp-stages``, the stage axis's width) into nearly-even chunks, and
the ``[mb, tokens, dim]`` token activations travel every boundary:

- **stage 0**: patchify -> embed + pos-embed -> first block chunk
- **stages 1..S-2**: a chunk of blocks each (uniform boundary shape —
  what makes the transformer the natural multi-stage pipeline)
- **stage S-1**: last chunk -> final LN -> mean-pool -> head ->
  weighted NLL

The microbatched ppermute schedule and its hand-written ``custom_vjp``
backward come from parallel/pipeline.py's S-stage engine (shared with
the CNN pipeline, parallel/pp.py, which stays at its natural 2 stages:
conv | dense); this module supplies the ViT stage bodies, composed from
the same models/vit.py helpers the single-device forward uses, so parity
(tests/test_pp_vit.py) is exact — the family has no dropout, hence no
mask-geometry caveat.  Under ``cfg.bf16`` the stage boundary travels at
bfloat16 (the engine discovers the activation aval via ``eval_shape``).

With tp_vit/sp3/ep, this completes the ViT family's parallelism matrix:
dp (vit_mnist.py default over the data axis), tp, sp, pp, ep.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.vit import (
    ViTConfig,
    apply_block,
    dense,
    layer_norm,
    patchify,
    tokens_to_logp,
)
from ..ops.adadelta import adadelta_update
from ..ops.attention import full_attention
from ..ops.loss import nll_loss
from .ddp import TrainState
from .mesh import DATA_AXIS
from .pipeline import STAGE_AXIS, make_pipeline_loss_multi
from ..utils.jax_compat import shard_map


def _stage_bounds(depth: int, num_stages: int) -> list[int]:
    """Block-index boundaries distributing ``depth`` blocks over stages
    as evenly as possible.  Floor-based (``i*depth // S``, never
    ``round`` — banker's rounding would flip the depth=7 S=2 split to
    4|3), so S=2 reproduces the round-2 ``depth // 2`` split exactly at
    every depth."""
    return [i * depth // num_stages for i in range(num_stages + 1)]


def _run_blocks(params: dict, tokens: jax.Array, cfg: ViTConfig,
                start: int, end: int) -> jax.Array:
    for i in range(start, end):
        tokens = apply_block(
            params["blocks"][str(i)], tokens, cfg, full_attention
        )
    return tokens


def _embed(params: dict, x: jax.Array, cfg: ViTConfig) -> jax.Array:
    """patchify + embed + pos-embed: [mb, 28, 28, 1] -> [mb, tokens, dim]
    (bf16 under cfg.bf16 — the boundary dtype)."""
    dt = jnp.bfloat16 if cfg.bf16 else x.dtype
    patches = patchify(x, cfg).astype(dt)
    return dense(patches, params["embed"]) + params["pos_embed"].astype(dt)


def _head_loss_sum(
    params: dict, tokens: jax.Array, y: jax.Array, w: jax.Array,
) -> jax.Array:
    """final LN + mean-pool + head + weighted NLL SUM."""
    tokens = layer_norm(tokens, params["ln_f"])
    pooled = tokens.astype(jnp.float32).mean(axis=1)
    logp = tokens_to_logp(params, pooled)
    return nll_loss(logp, y, w, reduction="sum")


def make_vit_pp_train_step(
    mesh: Mesh,
    cfg: ViTConfig,
    num_micro: int = 2,
    rho: float = 0.9,
    eps: float = 1e-6,
):
    """Build the jitted (data x stage) pipelined ViT train step for ANY
    stage count: the stage axis's width S splits the ``depth``
    transformer blocks into S nearly-even chunks (embed rides the first
    stage, LN/pool/head/loss the last), scheduled by the generic S-stage
    GPipe engine (parallel/pipeline.py:make_pipeline_loss_multi).

    ``step_fn(state, x, y, w, lr) -> (state, losses)`` with ``state``
    fully replicated, ``x/y/w`` sharded over ``data``, ``losses`` one
    local mean loss per data shard (the vit_mnist.py step signature).
    """
    num_stages = mesh.shape[STAGE_AXIS]
    if num_stages < 2:
        raise ValueError(
            f"pipeline needs a >= 2-wide '{STAGE_AXIS}' axis, got "
            f"{num_stages}"
        )
    if cfg.depth < num_stages:
        raise ValueError(
            f"pipeline needs depth >= {num_stages} blocks, got {cfg.depth}"
        )
    bounds = _stage_bounds(cfg.depth, num_stages)

    def first(params, x_mb, key, j):
        tokens = _embed(params, x_mb, cfg)
        return _run_blocks(params, tokens, cfg, bounds[0], bounds[1])

    def make_mid(start, end):
        def mid(params, act, key, j):
            return _run_blocks(params, act, cfg, start, end)

        return mid

    mids = [
        make_mid(bounds[s], bounds[s + 1]) for s in range(1, num_stages - 1)
    ]

    def last(params, act, y_mb, w_mb, key, j):
        tokens = _run_blocks(params, act, cfg, bounds[-2], bounds[-1])
        return _head_loss_sum(params, tokens, y_mb, w_mb)

    pipeline_loss = make_pipeline_loss_multi([first, *mids, last], num_micro)

    def local_step(state: TrainState, x, y, w, lr):
        n = x.shape[0]
        if n % num_micro:
            raise ValueError(
                f"shard batch {n} not divisible by {num_micro} microbatches"
            )
        mb = n // num_micro
        x_mbs = x.reshape(num_micro, mb, *x.shape[1:])
        y_mbs = y.reshape(num_micro, mb)
        w_mbs = w.reshape(num_micro, mb)
        denom = jnp.maximum(w.sum(), 1.0)
        # The ViT has no dropout; the engine's key slot is a dummy.
        key = jax.random.PRNGKey(0)

        def loss_fn(params):
            return pipeline_loss(params, x_mbs, y_mbs, w_mbs, key) / denom

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        grads = jax.lax.pmean(grads, DATA_AXIS)
        params, opt = adadelta_update(
            state.params, grads, state.opt, lr, rho, eps
        )
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(P(), P(DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_vit_eval_step(mesh: Mesh, cfg: ViTConfig, attention_fn=None):
    """Jitted data-parallel ViT eval step for any mesh with a ``data``
    axis (params replicated — the --pp eval path, mirroring the CNN's
    make_eval_step-under-pp): single-device forward on the local data
    shard + the psum'd (loss_sum, correct) totals every eval path shares.
    ``attention_fn`` overrides the dense default (the ``--flash`` kernel,
    ops/pallas_attention.py)."""
    from ..models.vit import vit_forward
    from ..ops.attention import full_attention

    if attention_fn is None:
        attention_fn = full_attention

    def local_eval(params, x, y, w):
        logp = vit_forward(params, x, cfg, attention_fn=attention_fn)
        loss_sum = nll_loss(logp, y, w, reduction="sum")
        correct = ((jnp.argmax(logp, axis=1) == y) * w).sum()
        return jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
