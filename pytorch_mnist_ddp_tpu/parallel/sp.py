"""Sequence/context parallelism: ring attention over a ``seq`` mesh axis.

The reference has no sequence dimension at all (SURVEY.md §5 "Long-context
/ sequence parallelism: N/A" — 28x28 images, no attention), so this module
is beyond-parity capability: the framework's long-context answer.  Tokens
are sharded over a ``seq`` axis; each device keeps its query block pinned
and the (key, value) blocks travel the ring with ``ppermute``, one hop per
step, folding into the online-softmax accumulator (ops/attention.py) until
every device has seen every block.  Communication is neighbor-only — the
pattern ICI is built for — and overlaps with the per-block compute under
XLA's latency-hiding scheduler; memory per device stays O(T/S) while the
attended context is the full T.

The same mesh carries data parallelism on its first axis, so the 2-D
``(data, seq)`` step scales batch and sequence independently.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops.attention import (
    block_update,
    finalize_block_acc,
    init_block_acc,
)
from .mesh import DATA_AXIS, make_2d_mesh
from ..utils.jax_compat import axis_size, pcast, shard_map, typeof

SEQ_AXIS = "seq"


def make_sp_mesh(
    num_data: int | None = None,
    num_seq: int = 1,
    devices=None,
) -> Mesh:
    """Build a ``(data, seq)`` mesh: the seq ring's every-hop ppermutes
    ride the adjacent, fastest ICI links (see mesh.make_2d_mesh)."""
    return make_2d_mesh(num_data, num_seq, SEQ_AXIS, devices)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Exact attention over the full (sharded) sequence via a k/v ring.

    Call inside ``shard_map`` with the token axis sharded over
    ``axis_name``.  ``q/k/v`` are the LOCAL blocks ``[b, T/S, h, d]``;
    ``kv_mask`` (optional ``[b, T/S]``, False = padding) travels the ring
    with its block so masked tokens are excluded wherever they visit.

    Exactness: ``block_update`` is order-invariant, so each device folding
    the S blocks in its own ring order reproduces dense softmax over all T
    tokens — parity with ``ops.attention.full_attention`` is pinned by
    tests/test_sp.py.  One jnp-stacked carry keeps the scan body a single
    fused (matmul + rescale + ppermute) program per hop.
    """
    size = axis_size(axis_name)
    b, t_local, h, d = q.shape
    perm = [(i, (i + 1) % size) for i in range(size)]

    # Fold the resident block first, then size-1 rotate-then-fold hops: no
    # hop is ever wasted (a rotate-after-fold loop of length `size` would
    # ship one final k/v exchange whose result is discarded — and a scan
    # body is one shared compiled program, so XLA cannot DCE it from just
    # the last iteration).
    acc = block_update(init_block_acc(b, h, t_local, d), q, k, v, kv_mask)

    # The scan body makes every carry component device-varying over the
    # ring axis AND over whatever axes the inputs already vary on (e.g. the
    # data axis of a 2-D (data, seq) mesh), so a component that starts
    # replicated must be cast varying up front to the UNION of those axes
    # or the carry is not type-stable under VMA tracking.  Axes a leaf
    # already varies on must be skipped: the cast is strictly
    # invariant->variant.
    target_vma = (
        {axis_name}
        | typeof(q).vma
        | typeof(k).vma
        | typeof(v).vma
        | (set() if kv_mask is None else typeof(kv_mask).vma)
    )

    def ensure_varying(leaf):
        missing = tuple(sorted(target_vma - set(typeof(leaf).vma)))
        if not missing:
            return leaf
        return pcast(leaf, missing, to="varying")

    if kv_mask is None:
        # Unmasked fast path: no mask travels the ring and block_update
        # skips both masking passes entirely.
        def hop(carry, _):
            acc, k, v = carry
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            acc = block_update(acc, q, k, v, None)
            return (acc, k, v), None

        (acc, _, _), _ = jax.lax.scan(
            hop, jax.tree.map(ensure_varying, (acc, k, v)), None,
            length=size - 1,
        )
    else:
        def hop(carry, _):
            acc, k, v, mask = carry
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            mask = jax.lax.ppermute(mask, axis_name, perm)
            acc = block_update(acc, q, k, v, mask)
            return (acc, k, v, mask), None

        (acc, _, _, _), _ = jax.lax.scan(
            hop, jax.tree.map(ensure_varying, (acc, k, v, kv_mask)),
            None, length=size - 1,
        )
    return finalize_block_acc(acc, q.dtype)


def ring_attention_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
) -> jax.Array:
    """:func:`ring_attention` with each hop's fold fused into the Pallas
    partial-accumulation kernel (ops/pallas_attention.py:
    flash_block_update) — the two long-context layers composed: the ring
    moves k/v blocks BETWEEN chips, the kernel fuses scores + rescale +
    value-matmul WITHIN one, and the online-softmax state never leaves
    the kernel's lane-broadcast layout between hops (fold/pad once,
    finalize once).  Maskless (the family has no token padding; the
    masked path stays on :func:`ring_attention`).  Exactness contract and
    parity pins: tests/test_flash.py."""
    from ..ops import pallas_attention as pa

    size = axis_size(axis_name)
    b, t_local, h, d = q.shape
    tp = pa.flash_pad_len(t_local)
    scale = 1.0 / float(d) ** 0.5
    q3 = pa.flash_fold_pad(q, tp)
    k3 = pa.flash_fold_pad(k, tp)
    v3 = pa.flash_fold_pad(v, tp)
    m, l, a = pa.flash_ring_state(b * h, tp, q3.shape[-1])
    m, l, a = pa.flash_block_update(m, l, a, q3, k3, v3, t_local, scale)

    perm = [(i, (i + 1) % size) for i in range(size)]
    # Same VMA discipline as ring_attention when tracking is on (the sp
    # steps keep check_vma=True — their transpose-inserted psums are
    # load-bearing); under a check_vma=False shard_map every vma is
    # empty and no cast exists to make.
    input_vma = typeof(q3).vma | typeof(k3).vma | typeof(v3).vma
    target_vma = ({axis_name} | input_vma) if input_vma else set()

    def ensure_varying(leaf):
        missing = tuple(sorted(target_vma - set(typeof(leaf).vma)))
        if not missing:
            return leaf
        return pcast(leaf, missing, to="varying")

    def hop(carry, _):
        m, l, a, k3, v3 = carry
        k3 = jax.lax.ppermute(k3, axis_name, perm)
        v3 = jax.lax.ppermute(v3, axis_name, perm)
        m, l, a = pa.flash_block_update(m, l, a, q3, k3, v3, t_local, scale)
        return (m, l, a, k3, v3), None

    (m, l, a, _, _), _ = jax.lax.scan(
        hop, jax.tree.map(ensure_varying, (m, l, a, k3, v3)), None,
        length=size - 1,
    )
    return pa.flash_ring_finalize(m, l, a, b, h, t_local, d, q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    use_flash: bool = False,
) -> jax.Array:
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses pattern) —
    the OTHER canonical long-context strategy next to the ring.

    Where the ring keeps queries pinned and rotates k/v blocks S-1 hops,
    Ulysses re-shards ONCE per attention: an ``all_to_all`` over the seq
    axis trades the token sharding for a head sharding, so each device
    holds the FULL sequence for ``heads/S`` of the heads, runs ordinary
    dense attention locally (optionally the fused Pallas kernel — the
    production long-context recipe), and a second ``all_to_all`` restores
    the token sharding.  Two collectives total vs the ring's S-1 hops;
    memory per device is O(T·h/S) during attention (vs the ring's
    O(T/S·h)) — the canonical tradeoff.  Requires ``heads % S == 0``
    (checked at step construction).

    Call inside ``shard_map`` with ``q/k/v`` the LOCAL token blocks
    ``[b, T/S, h, d]``; token shards are contiguous in ring order, so the
    all_to_all's peer-ordered concat reassembles the global token order
    exactly.  Maskless, like the flash paths (the family has no token
    padding)."""
    from ..ops.attention import full_attention
    from ..ops.pallas_attention import flash_attention

    # [b, T/S, h, d] -> [b, T, h/S, d]: split heads over peers, gather
    # every peer's token block.
    to_heads = lambda x: jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    fn = flash_attention if use_flash else full_attention
    out = fn(to_heads(q), to_heads(k), to_heads(v))
    # [b, T, h/S, d] -> [b, T/S, h, d]: the exact inverse.
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


# ---------------------------------------------------------------------------
# Sequence-parallel ViT training: the 2-D (data, seq) step.
# ---------------------------------------------------------------------------


def _check_token_divisibility(cfg, mesh: Mesh, impl: str = "ring") -> None:
    """A non-divisible token count would silently drop the trailing
    ``num_tokens % num_seq`` tokens from every shard's slice (and skew the
    mean-pool denominator) — fail loudly at step-construction time.
    Ulysses additionally needs the heads to split over the seq axis."""
    num_seq = mesh.shape[SEQ_AXIS]
    if cfg.num_tokens % num_seq:
        raise ValueError(
            f"num_tokens={cfg.num_tokens} not divisible by the seq axis "
            f"({num_seq}); pick a patch grid divisible by the mesh"
        )
    if impl == "ulysses" and cfg.heads % num_seq:
        raise ValueError(
            f"--sp-impl ulysses shards heads over the seq axis: "
            f"heads={cfg.heads} not divisible by {num_seq}"
        )
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown sp impl {impl!r}")


def _sp_vit_forward(
    params: dict, x: jax.Array, cfg, use_flash: bool = False,
    impl: str = "ring",
) -> jax.Array:
    """The ViT forward over a TOKEN SHARD, inside shard_map.

    ``x`` is the local data-shard of images, replicated over ``seq``; this
    device embeds only its ``T/S`` token slice (patch rows and pos-embed
    rows selected by mesh position), runs every per-token op locally, and
    attends over the full sequence through the ring.  The mean-pool is a
    token-sum psum over ``seq`` — after it, logits/loss are seq-invariant.
    Composes the SAME helpers as models/vit.py's single-device forward.
    """
    from ..models.vit import (
        apply_block,
        dense,
        layer_norm,
        patchify,
        tokens_to_logp,
    )

    num_seq = axis_size(SEQ_AXIS)
    t_local = cfg.num_tokens // num_seq
    start = jax.lax.axis_index(SEQ_AXIS) * t_local

    dt = jnp.bfloat16 if cfg.bf16 else x.dtype
    patches = jax.lax.dynamic_slice_in_dim(
        patchify(x, cfg), start, t_local, axis=1
    ).astype(dt)
    pos = jax.lax.dynamic_slice_in_dim(
        params["pos_embed"], start, t_local, axis=0
    ).astype(dt)
    tokens = dense(patches, params["embed"]) + pos
    if impl == "ulysses":
        attn = lambda q, k, v: ulysses_attention(
            q, k, v, SEQ_AXIS, use_flash=use_flash
        )
    else:
        ring = ring_attention_flash if use_flash else ring_attention
        attn = lambda q, k, v: ring(q, k, v, SEQ_AXIS)
    def block(bp, tokens):
        # cfg and attn are closed over, NOT passed as static args: the
        # attn lambda above is constructed fresh per step build, and a
        # static-argnum lambda would key a new trace-cache entry each
        # time (round-3 advisor finding).
        return apply_block(bp, tokens, cfg, attn)

    if cfg.remat:
        # Same remat contract as the single-device trunk (_vit_trunk):
        # collectives inside the block (the ring/all_to_all) replay in
        # backward too — jax.checkpoint handles them like any other op.
        block = jax.checkpoint(block)
    for i in range(cfg.depth):
        tokens = block(params["blocks"][str(i)], tokens)
    tokens = layer_norm(tokens, params["ln_f"])
    # fp32 pool (the same head/log_softmax numerics contract as the
    # single-device trunk).
    pooled = (
        jax.lax.psum(tokens.astype(jnp.float32).sum(axis=1), SEQ_AXIS)
        / cfg.num_tokens
    )
    return tokens_to_logp(params, pooled)


def make_sp_train_step(mesh: Mesh, cfg, rho: float = 0.9, eps: float = 1e-6,
                       use_flash: bool = False, impl: str = "ring"):
    """Build the jitted 2-D (data x seq) ViT train step.

    ``step_fn(state, x, y, w, lr) -> (state, losses)`` with ``state`` a
    fully-replicated ddp.TrainState over ViT params, ``x/y/w`` sharded over
    ``data``, ``losses`` one local loss per data shard.  Gradient
    semantics mirror parallel/tp.py: under VMA tracking the transpose
    already psums each param's cotangent over both mesh axes (the seq-axis
    sum IS the full-sequence gradient — each shard contributes distinct
    tokens), so what arrives is the data-axis SUM of local-mean grads;
    divide by the data degree for DDP mean semantics.
    """
    from jax.sharding import PartitionSpec as P

    from ..ops.adadelta import adadelta_update
    from ..ops.loss import nll_loss
    from .ddp import TrainState

    _check_token_divisibility(cfg, mesh, impl)
    num_data = mesh.shape[DATA_AXIS]

    def local_step(state: TrainState, x, y, w, lr):
        def loss_fn(params):
            logp = _sp_vit_forward(
                params, x, cfg, use_flash=use_flash, impl=impl
            )
            return nll_loss(logp, y, w, reduction="mean")

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        grads = jax.tree.map(lambda g: g / num_data, grads)
        params, opt = adadelta_update(
            state.params, grads, state.opt, lr, rho, eps
        )
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(P(), P(DATA_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_sp_eval_step(mesh: Mesh, cfg, use_flash: bool = False,
                      impl: str = "ring"):
    """Jitted (data x seq) eval step: sequence-parallel forward (ring or
    ulysses) + the psum'd (loss_sum, correct) totals of
    ddp.make_eval_step — identical printed numbers, full-mesh
    participation."""
    from jax.sharding import PartitionSpec as P

    from ..ops.loss import nll_loss

    _check_token_divisibility(cfg, mesh, impl)

    def local_eval(params, x, y, w):
        logp = _sp_vit_forward(
            params, x, cfg, use_flash=use_flash, impl=impl
        )
        loss_sum = nll_loss(logp, y, w, reduction="sum")
        correct = ((jnp.argmax(logp, axis=1) == y) * w).sum()
        return jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    )
    return jax.jit(sharded)
