"""The generic S-stage GPipe schedule: microbatched ppermute + custom_vjp.

parallel/pp.py introduced the 2-stage form for the reference CNN (conv
stage -> dense stage); parallel/pp_vit.py pipelines the ViT's transformer
blocks over ANY stage count with the generalized engine.  The schedule is
model-agnostic — what moves between devices is "the stage-boundary
activation", whatever its (uniform) shape — so it lives here once,
parameterized by the list of stage bodies (``make_pipeline_loss_multi``;
the 2-stage ``make_pipeline_loss`` API is a wrapper over the same code).

Schedule (S stages, M microbatches, ``M + S - 1`` ticks each direction,
driven by ``lax.scan`` with one ``lax.ppermute`` hop per tick):

- **forward**: stage ``s`` processes microbatch ``j`` at tick ``s + j``
  — stage 0 consumes raw microbatches, middle stages the activation that
  arrived on the ring one tick earlier, the last stage accumulates the
  loss; every arriving activation is stashed for the backward pass.
- **backward** (reverse ring): stage ``s`` rematerializes microbatch
  ``j`` at tick ``(S-1-s) + (M-1-j)`` under ``jax.vjp`` (the same
  ``j``-folded keys, so dropout masks replay exactly), accumulates its
  param grads, and ppermutes the input-activation cotangent back one
  hop, where stage ``s-1`` consumes it the next tick.

Each device executes ONLY its own stage's FLOPs: body selection is a
runtime ``lax.switch`` on the device's stage-axis index, with the
activity test in a ``lax.cond`` PREDICATE (idle ticks take the free
zeros branch).  Transposing such a cond nested in this scan+ppermute
SIGABRTs the XLA:CPU runtime (jaxlib in this image), which is why the
backward schedule is hand-written under ``jax.custom_vjp`` — autodiff
never transposes anything, and the pipeline's collective pattern stays
fully explicit: the per-tick activation/cotangent ppermute plus one
stage-axis ``psum`` of the (disjoint) per-stage grad trees.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import MODEL_AXIS

STAGE_AXIS = MODEL_AXIS  # the reserved second mesh axis doubles as stages
NUM_STAGES = 2


def _float0_zeros(v: jax.Array):
    """Cotangent for a non-differentiable (integer) primal."""
    return np.zeros(v.shape, jax.dtypes.float0)


def make_pipeline_loss_multi(stage_fns, num_micro: int):
    """Build ``pipeline_loss(params, x_mbs, y_mbs, w_mbs, key) ->
    loss_sum`` — the scheduled, ``custom_vjp``-differentiable S-stage
    GPipe pipeline over one data shard, for use inside ``shard_map`` with
    ``check_vma=False`` over an S-wide stage axis.

    ``stage_fns`` is a list of S >= 2 bodies with the uniform contract:

    - ``stage_fns[0](params, x_mb, key, j) -> act`` — consumes the raw
      microbatch;
    - ``stage_fns[s](params, act, key, j) -> act`` for the middle stages
      (every boundary activation must share ONE shape/dtype — true for
      transformer-block stacks, where the boundary is always
      ``[mb, t, dim]``);
    - ``stage_fns[-1](params, act, y_mb, w_mb, key, j) -> loss_sum``.

    Schedule indexing (the whole generalization): stage ``s`` processes
    microbatch ``j`` at forward tick ``s + j`` (the activation it emits
    arrives at ``s+1`` one tick later) and at backward tick
    ``(S-1-s) + (M-1-j)`` (its input-cotangent emission reaches ``s-1``
    one tick later) — for S=2 this reduces exactly to the round-2
    schedule.  Total ticks each direction: ``M + S - 1``.

    ``x_mbs/y_mbs/w_mbs`` are ``[num_micro, mb, ...]``; the returned loss
    is the stage-psum'd SUM over the shard (callers divide by their own
    weight total).  The boundary activation's shape/dtype is discovered
    from ``stage_fns[0]`` via ``jax.eval_shape`` — bf16 boundaries travel
    at their native width.
    """
    if num_micro < 1:
        raise ValueError(f"num_micro must be >= 1, got {num_micro}")
    num_stages = len(stage_fns)
    if num_stages < 2:
        raise ValueError(f"need >= 2 stage bodies, got {num_stages}")
    first_fn, last_fn = stage_fns[0], stage_fns[-1]
    mid_fns = list(stage_fns[1:-1])
    ring = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    ring_rev = [(dst, src) for src, dst in ring]
    ticks = num_micro + num_stages - 1

    def _act_zeros(params, x_mbs, key):
        a = jax.eval_shape(
            lambda p, x, k: first_fn(p, x, k, 0), params, x_mbs[0], key
        )
        return jnp.zeros(a.shape, a.dtype)

    def _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key):
        """Returns (stage-psum'd loss SUM over this data shard, stashed
        arriving activations [ticks, mb, ...])."""
        stage = jax.lax.axis_index(STAGE_AXIS)
        zero_act = _act_zeros(params, x_mbs, key)

        def tick(carry, t):
            in_flight = carry  # activation that arrived at this device

            # This device's microbatch at tick t; activity lives in the
            # cond PREDICATE so idle ticks take the zeros branch for free
            # (the cond is never transposed — custom_vjp below).
            j = t - stage
            active = jnp.logical_and(j >= 0, j < num_micro)
            jc = jnp.clip(j, 0, num_micro - 1)
            x_mb = jax.lax.dynamic_index_in_dim(x_mbs, jc, keepdims=False)
            y_mb = jax.lax.dynamic_index_in_dim(y_mbs, jc, keepdims=False)
            w_mb = jax.lax.dynamic_index_in_dim(w_mbs, jc, keepdims=False)

            def run_first():
                return first_fn(params, x_mb, key, jc), jnp.float32(0.0)

            def run_mid(fn):
                return lambda: (fn(params, in_flight, key, jc), jnp.float32(0.0))

            def run_last():
                return zero_act, last_fn(
                    params, in_flight, y_mb, w_mb, key, jc
                )

            branches = [run_first, *[run_mid(fn) for fn in mid_fns], run_last]
            out, part = jax.lax.cond(
                active,
                lambda: jax.lax.switch(stage, branches),
                lambda: (zero_act, jnp.float32(0.0)),
            )
            moved = jax.lax.ppermute(out, STAGE_AXIS, ring)
            return moved, (part, in_flight)

        _, (parts, stash) = jax.lax.scan(tick, zero_act, jnp.arange(ticks))
        return jax.lax.psum(parts.sum(), STAGE_AXIS), stash

    @jax.custom_vjp
    def pipeline_loss(params, x_mbs, y_mbs, w_mbs, key):
        loss_sum, _ = _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key)
        return loss_sum

    def pipeline_loss_fwd(params, x_mbs, y_mbs, w_mbs, key):
        loss_sum, stash = _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key)
        return loss_sum, (params, x_mbs, y_mbs, w_mbs, key, stash)

    def pipeline_loss_bwd(res, g):
        """The reverse schedule, hand-written (never a cond transpose).

        Backward tick sigma: stage s rematerializes microbatch
        ``j = M - 1 - (sigma - (S-1-s))`` under ``jax.vjp`` — the last
        stage seeds with ``g``, every other stage with the cotangent that
        just arrived on the reverse ring; its own input cotangent
        ppermutes back one hop.  The stashed activation each stage needs
        is the one that ARRIVED at forward tick ``s + j``.  Param-grad
        trees are disjoint per stage; one stage-axis psum at the end
        makes every device hold the full gradient."""
        params, x_mbs, y_mbs, w_mbs, key, stash = res
        stage = jax.lax.axis_index(STAGE_AXIS)
        zero_grads = jax.tree.map(jnp.zeros_like, params)
        zero_ga = _act_zeros(params, x_mbs, key)

        def tick(carry, s):
            g_act_in, acc = carry
            j = num_micro - 1 - (s - (num_stages - 1 - stage))
            active = jnp.logical_and(j >= 0, j < num_micro)
            jc = jnp.clip(j, 0, num_micro - 1)
            # The activation that arrived here at forward tick stage + j.
            act = jax.lax.dynamic_index_in_dim(
                stash, jnp.clip(stage + jc, 0, ticks - 1), keepdims=False
            )
            x_mb = jax.lax.dynamic_index_in_dim(x_mbs, jc, keepdims=False)
            y_mb = jax.lax.dynamic_index_in_dim(y_mbs, jc, keepdims=False)
            w_mb = jax.lax.dynamic_index_in_dim(w_mbs, jc, keepdims=False)

            def bwd_first():
                _, vjp = jax.vjp(
                    lambda p: first_fn(p, x_mb, key, jc), params
                )
                (gp,) = vjp(g_act_in)
                return gp, zero_ga

            def bwd_mid(fn):
                def run():
                    _, vjp = jax.vjp(
                        lambda p, a: fn(p, a, key, jc), params, act
                    )
                    return vjp(g_act_in)

                return run

            def bwd_last():
                _, vjp = jax.vjp(
                    lambda p, a: last_fn(p, a, y_mb, w_mb, key, jc),
                    params, act,
                )
                return vjp(g)

            branches = [bwd_first, *[bwd_mid(fn) for fn in mid_fns], bwd_last]
            gp, ga = jax.lax.cond(
                active,
                lambda: jax.lax.switch(stage, branches),
                lambda: (zero_grads, zero_ga),
            )
            acc = jax.tree.map(jnp.add, acc, gp)
            moved = jax.lax.ppermute(ga, STAGE_AXIS, ring_rev)
            return (moved, acc), None

        (_, acc), _ = jax.lax.scan(
            tick, (zero_ga, zero_grads), jnp.arange(ticks)
        )
        # Disjoint per-stage trees -> full gradient everywhere.
        acc = jax.lax.psum(acc, STAGE_AXIS)
        return (
            acc,
            jnp.zeros_like(x_mbs),
            _float0_zeros(y_mbs),
            jnp.zeros_like(w_mbs),
            _float0_zeros(key),
        )

    pipeline_loss.defvjp(pipeline_loss_fwd, pipeline_loss_bwd)
    return pipeline_loss


def make_pipeline_loss(stage0_fn, stage1_fn, num_micro: int):
    """The 2-stage special case (the round-2 API, unchanged): conv |
    dense for the CNN (parallel/pp.py), block-halves for the ViT
    (parallel/pp_vit.py)."""
    return make_pipeline_loss_multi([stage0_fn, stage1_fn], num_micro)
