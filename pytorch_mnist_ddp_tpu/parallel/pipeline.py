"""The generic 2-stage GPipe schedule: microbatched ppermute + custom_vjp.

parallel/pp.py introduced this schedule for the reference CNN (conv stage
-> dense stage); parallel/pp_vit.py pipelines the ViT's transformer blocks
with it.  The schedule itself is model-agnostic — what moves between
devices is "the stage-boundary activation", whatever its shape — so it
lives here once, parameterized by the two stage bodies:

- ``stage0_fn(params, x_mb, key, j) -> act``: the first half of the model
  on microbatch ``j`` (``key`` is the caller's dropout key; stateless
  models ignore it);
- ``stage1_fn(params, act, y_mb, w_mb, key, j) -> loss_sum``: the second
  half through the weighted NLL SUM for microbatch ``j``.

Schedule (NUM_STAGES = 2, ``num_micro`` microbatches, driven by
``lax.scan`` with one ``lax.ppermute`` hop per tick):

- **forward** (``num_micro + 1`` ticks): stage 0 runs microbatch ``t``
  while stage 1 consumes the activation sent at ``t - 1`` and accumulates
  the loss; arriving activations are stashed for the backward pass.
- **backward** (``num_micro + 1`` ticks, reverse order): stage 1 re-runs
  its microbatch body under ``jax.vjp`` (rematerialization — the same
  ``j``-folded keys, so dropout masks replay exactly), accumulates its
  param grads, and ppermutes the activation cotangent back; stage 0
  consumes it one tick later.

Each device executes ONLY its own stage's FLOPs: stage selection is a
runtime ``lax.cond`` on the device's stage-axis index.  Transposing such
a ``cond`` nested in this scan+ppermute SIGABRTs the XLA:CPU runtime
(jaxlib in this image), which is why the backward schedule is hand-written
under ``jax.custom_vjp`` — autodiff never transposes anything, and the
pipeline's collective pattern stays fully explicit: the per-tick
activation/cotangent ppermute plus one stage-axis ``psum`` of the
(disjoint) per-stage grad trees.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import MODEL_AXIS

STAGE_AXIS = MODEL_AXIS  # the reserved second mesh axis doubles as stages
NUM_STAGES = 2


def _float0_zeros(v: jax.Array):
    """Cotangent for a non-differentiable (integer) primal."""
    return np.zeros(v.shape, jax.dtypes.float0)


def make_pipeline_loss(stage0_fn, stage1_fn, num_micro: int):
    """Build ``pipeline_loss(params, x_mbs, y_mbs, w_mbs, key) ->
    loss_sum`` — the scheduled, ``custom_vjp``-differentiable 2-stage
    pipeline over one data shard, for use inside ``shard_map`` with
    ``check_vma=False``.

    ``x_mbs/y_mbs/w_mbs`` are ``[num_micro, mb, ...]``; the returned loss
    is the stage-psum'd SUM over the shard (callers divide by their own
    weight total).  The stage-boundary activation's shape/dtype is
    discovered from ``stage0_fn`` via ``jax.eval_shape`` — bf16 boundaries
    travel at their native width.
    """
    if num_micro < 1:
        raise ValueError(f"num_micro must be >= 1, got {num_micro}")
    ring = [(i, (i + 1) % NUM_STAGES) for i in range(NUM_STAGES)]
    ring_rev = [(dst, src) for src, dst in ring]
    ticks = num_micro + NUM_STAGES - 1

    def _act_zeros(params, x_mbs, key):
        a = jax.eval_shape(
            lambda p, x, k: stage0_fn(p, x, k, 0), params, x_mbs[0], key
        )
        return jnp.zeros(a.shape, a.dtype)

    def _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key):
        """Returns (stage-psum'd loss SUM over this data shard, stashed
        arriving activations [ticks, mb, ...])."""
        stage = jax.lax.axis_index(STAGE_AXIS)
        zero_act = _act_zeros(params, x_mbs, key)

        def tick(carry, t):
            in_flight = carry  # activation that arrived at this device

            # stage 0 forwards microbatch t; the activity test lives in the
            # cond PREDICATE, so idle ticks take the zeros branch for free
            # (the cond is never transposed — custom_vjp below).
            t0 = jnp.clip(t, 0, num_micro - 1)
            x_mb = jax.lax.dynamic_index_in_dim(x_mbs, t0, keepdims=False)
            out = jax.lax.cond(
                jnp.logical_and(stage == 0, t < num_micro),
                lambda: stage0_fn(params, x_mb, key, t0),
                lambda: zero_act,
            )

            # stage 1 consumes the block sent at tick t-1 (idle at t=0
            # takes the zero branch).
            t1 = jnp.clip(t - 1, 0, num_micro - 1)
            y_mb = jax.lax.dynamic_index_in_dim(y_mbs, t1, keepdims=False)
            w_mb = jax.lax.dynamic_index_in_dim(w_mbs, t1, keepdims=False)
            part = jax.lax.cond(
                jnp.logical_and(stage == 1, t >= 1),
                lambda: stage1_fn(params, in_flight, y_mb, w_mb, key, t1),
                lambda: jnp.float32(0.0),
            )

            moved = jax.lax.ppermute(out, STAGE_AXIS, ring)
            return moved, (part, in_flight)

        _, (parts, stash) = jax.lax.scan(tick, zero_act, jnp.arange(ticks))
        return jax.lax.psum(parts.sum(), STAGE_AXIS), stash

    @jax.custom_vjp
    def pipeline_loss(params, x_mbs, y_mbs, w_mbs, key):
        loss_sum, _ = _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key)
        return loss_sum

    def pipeline_loss_fwd(params, x_mbs, y_mbs, w_mbs, key):
        loss_sum, stash = _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key)
        return loss_sum, (params, x_mbs, y_mbs, w_mbs, key, stash)

    def pipeline_loss_bwd(res, g):
        """The reverse schedule, hand-written (never a cond transpose).

        Tick s: stage 1 rematerializes microbatch ``num_micro - 1 - s``
        under ``jax.vjp`` (grads for its params + the activation
        cotangent, scaled by ``g``), ppermutes the cotangent back; stage 0
        consumes it at tick ``s + 1``.  Param-grad trees are disjoint per
        stage; one stage-axis psum at the end makes every device hold the
        full gradient."""
        params, x_mbs, y_mbs, w_mbs, key, stash = res
        stage = jax.lax.axis_index(STAGE_AXIS)
        zero_grads = jax.tree.map(jnp.zeros_like, params)
        zero_ga = _act_zeros(params, x_mbs, key)

        def tick(carry, s):
            g_act_in, acc = carry

            def s1_body():
                # stage 1: microbatch j arrived at forward tick j+1
                j = jnp.clip(num_micro - 1 - s, 0, num_micro - 1)
                act = jax.lax.dynamic_index_in_dim(stash, j + 1, keepdims=False)
                y_mb = jax.lax.dynamic_index_in_dim(y_mbs, j, keepdims=False)
                w_mb = jax.lax.dynamic_index_in_dim(w_mbs, j, keepdims=False)
                _, vjp = jax.vjp(
                    lambda p, a: stage1_fn(p, a, y_mb, w_mb, key, j),
                    params, act,
                )
                gp, ga = vjp(g)
                return gp, ga

            def s0_body():
                # stage 0: the cotangent arriving at tick s is for the
                # microbatch stage 1 processed at tick s-1
                j = jnp.clip(num_micro - s, 0, num_micro - 1)
                x_mb = jax.lax.dynamic_index_in_dim(x_mbs, j, keepdims=False)
                _, vjp = jax.vjp(
                    lambda p: stage0_fn(p, x_mb, key, j), params
                )
                gp, = vjp(g_act_in)
                return gp, zero_ga

            def idle():
                return zero_grads, zero_ga

            # Activity in the PREDICATES: each device's idle tick takes the
            # free zeros branch instead of computing-then-masking.
            gp, ga = jax.lax.cond(
                jnp.logical_and(stage == 1, s < num_micro),
                s1_body,
                lambda: jax.lax.cond(
                    jnp.logical_and(stage == 0, s >= 1), s0_body, idle
                ),
            )
            acc = jax.tree.map(jnp.add, acc, gp)
            moved = jax.lax.ppermute(ga, STAGE_AXIS, ring_rev)
            return (moved, acc), None

        (_, acc), _ = jax.lax.scan(
            tick, (zero_ga, zero_grads), jnp.arange(ticks)
        )
        # Disjoint per-stage trees -> full gradient everywhere.
        acc = jax.lax.psum(acc, STAGE_AXIS)
        return (
            acc,
            jnp.zeros_like(x_mbs),
            _float0_zeros(y_mbs),
            jnp.zeros_like(w_mbs),
            _float0_zeros(key),
        )

    pipeline_loss.defvjp(pipeline_loss_fwd, pipeline_loss_bwd)
    return pipeline_loss
