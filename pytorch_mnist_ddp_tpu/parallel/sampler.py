"""Index sampling with DistributedSampler-parity semantics (SURVEY.md N5/N6).

The reference uses three torch samplers (reference mnist_ddp.py:161-165):

- ``DistributedSampler(train set)`` in distributed mode: pads the dataset to
  ``ceil(N/world) * world`` samples by repeating leading indices so every
  rank draws an equal count, shards by ``indices[rank::world]``, and
  reshuffles each epoch from an epoch-seeded generator activated by
  ``set_epoch(epoch)`` (mnist_ddp.py:180-181).
- ``RandomSampler`` for non-distributed train shuffle (mnist_ddp.py:164).
- ``SequentialSampler`` for deterministic eval order (mnist_ddp.py:165).

This module reproduces those *semantics* (equal per-rank counts, disjoint
cover modulo padding, epoch-seeded reshuffle, deterministic eval) with
numpy PRNG.  The exact permutation values differ from torch's Mersenne
generator — the contract preserved is the semantic one (SURVEY.md §4
'Sampler contract tests').
"""

from __future__ import annotations

import numpy as np


def epoch_indices(
    n: int,
    world_size: int = 1,
    rank: int = 0,
    epoch: int = 0,
    seed: int = 0,
    shuffle: bool = True,
    return_valid: bool = False,
) -> np.ndarray | tuple[np.ndarray, np.ndarray]:
    """Per-rank sample indices for one epoch.

    With ``world_size == 1`` and ``shuffle`` this is RandomSampler; with
    ``shuffle=False`` it is SequentialSampler; otherwise it implements the
    DistributedSampler contract: pad to divisible, epoch-seeded permutation,
    strided rank slice.

    ``return_valid=True`` additionally returns a bool mask marking entries
    that are real samples rather than padding duplicates.  Training keeps
    the duplicates live (torch's DistributedSampler trains on them too);
    eval masks them so global loss/accuracy totals count every test sample
    exactly once (see data/loader.py ``mask_padding``).
    """
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    if shuffle:
        # seed + epoch mirrors torch's DistributedSampler generator seeding;
        # a fresh permutation per epoch is the set_epoch(...) behavior.
        indices = np.random.RandomState(seed + epoch).permutation(n)
    else:
        indices = np.arange(n)
    if world_size == 1:
        return (indices, np.ones(n, bool)) if return_valid else indices
    num_samples = -(-n // world_size)  # ceil
    total = num_samples * world_size
    if total > n:
        # Pad by repeating the permutation CYCLICALLY (np.resize), exactly
        # torch's DistributedSampler padding.  A single concatenation of
        # indices[:total-n] under-fills whenever the padding exceeds n
        # (world_size > 2n) — found by the hypothesis contract test with
        # n=1, world_size=3.
        indices = np.resize(indices, total)
    positions = np.arange(rank, total, world_size)
    if return_valid:
        return indices[positions], positions < n
    return indices[positions]


def per_rank_count(n: int, world_size: int) -> int:
    """Samples each rank draws per epoch (after padding)."""
    return -(-n // world_size)
