"""Tensor parallelism for the ViT family: Megatron-style sharded blocks.

The reference is data-parallel only (SURVEY.md §2c); parallel/tp.py makes
the ``model`` mesh axis real for the CNN's classifier MLP.  This module
extends that axis to the attention family — the layout every transformer
framework ships as "tensor parallelism":

- **qkv column-parallel**: the projection kernel ``[dim, heads*3*head_dim]``
  splits over ``model`` on its output features.  The head-major qkv layout
  (models/vit.py:_attn_sublayer) makes a contiguous split land whole heads
  — each shard computes attention for its ``heads/M`` heads with zero
  communication (softmax is per-head).
- **proj row-parallel**: kernel ``[dim, dim]`` splits on its input dim,
  which is exactly the head-major flatten of the local attention output;
  ONE ``psum`` over ``model`` completes the residual branch.
- **MLP**: ``mlp_in`` column-parallel (gelu is feature-elementwise, no
  comm), ``mlp_out`` row-parallel — the second and last ``psum``.
- embed / pos_embed / LayerNorms / classifier head stay replicated (tiny,
  and LN needs full-width statistics anyway).

Two psums per block per direction — the canonical Megatron count.  The
transpose rule turns each forward psum into identity on the partial-sum
path and each replicated-param use into a model-axis grad psum, so
gradient semantics arrive exactly as in parallel/tp.py: the data-axis SUM
of local-mean grads, divided here by the data degree for DDP mean
semantics.  The Adadelta update runs on local shards (elementwise, sharded
state exact).

Composes with the ``data`` axis as a 2-D ``(data, model)`` mesh, and with
sequence parallelism as the 3-D ``(data, seq, model)`` step in
parallel/sp3.py — forward math, init, loss, and update are the same
functions the single-device ViT path uses; parity is pinned by
tests/test_tp_vit.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.vit import ViTConfig, dense, layer_norm, patchify, tokens_to_logp
from ..ops.adadelta import AdadeltaState, adadelta_update
from ..ops.attention import full_attention
from ..ops.loss import nll_loss
from .ddp import TrainState
from .mesh import DATA_AXIS, MODEL_AXIS, place_tree
from ..utils.jax_compat import axis_size, shard_map


def _check_head_divisibility(cfg: ViTConfig, mesh: Mesh) -> None:
    num_model = mesh.shape[MODEL_AXIS]
    if cfg.heads % num_model:
        raise ValueError(
            f"heads={cfg.heads} not divisible by the model axis "
            f"({num_model}); attention shards by whole heads"
        )
    if cfg.mlp_dim % num_model:
        raise ValueError(
            f"mlp_dim={cfg.mlp_dim} not divisible by the model axis "
            f"({num_model})"
        )


def vit_tp_param_specs(cfg: ViTConfig) -> dict:
    """PartitionSpecs for the ViT param tree under (data, model) sharding:
    qkv/mlp_in column-parallel, proj/mlp_out row-parallel, rest replicated.
    """
    col = {"kernel": P(None, MODEL_AXIS), "bias": P(MODEL_AXIS)}
    # Row-parallel bias is added once, after the psum — replicated.
    row = {"kernel": P(MODEL_AXIS, None), "bias": P()}
    rep = {"kernel": P(), "bias": P()}
    ln = {"scale": P(), "bias": P()}
    return {
        "embed": dict(rep),
        "pos_embed": P(),
        "head": dict(rep),
        "ln_f": dict(ln),
        "blocks": {
            str(i): {
                "ln1": dict(ln),
                "qkv": dict(col),
                "proj": dict(row),
                "ln2": dict(ln),
                "mlp_in": dict(col),
                "mlp_out": dict(row),
            }
            for i in range(cfg.depth)
        },
    }


def vit_tp_state_specs(cfg: ViTConfig):
    """Specs for the full TrainState: Adadelta accumulators shard exactly
    like their params (one definition for placement AND step specs)."""
    ps = vit_tp_param_specs(cfg)
    return TrainState(
        params=ps, opt=AdadeltaState(square_avg=ps, acc_delta=ps), step=P()
    )


def shard_vit_tp_state(state: TrainState, mesh: Mesh, cfg: ViTConfig):
    """Place a host TrainState onto the mesh with ViT-TP shardings
    (mesh.place_tree recipe)."""
    return place_tree(state, vit_tp_state_specs(cfg), mesh)


def _row(x: jax.Array, p: dict) -> jax.Array:
    """Row-parallel dense: local partial product, completed by one psum
    over ``model``; the replicated bias is added after the reduction."""
    part = x @ p["kernel"].astype(x.dtype)
    return jax.lax.psum(part, MODEL_AXIS) + p["bias"].astype(x.dtype)


def _tp_block(
    bp: dict,
    x: jax.Array,
    cfg: ViTConfig,
    heads_local: int,
    attention_fn=full_attention,
):
    """One pre-LN transformer block over a model shard: local heads, local
    MLP features, two psums (proj, mlp_out).  ``attention_fn`` is injected
    exactly as in models/vit.py — parallel/sp3.py passes ring attention to
    run this same block over a (token, head) shard."""
    b, t, _ = x.shape
    h = layer_norm(x, bp["ln1"])
    # Column-parallel layers reuse models/vit.py dense(): the local
    # kernel/bias shard IS just a narrower dense layer.
    qkv = dense(h, bp["qkv"]).reshape(b, t, heads_local, 3, cfg.head_dim)
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    attn = attention_fn(q, k, v).reshape(b, t, heads_local * cfg.head_dim)
    x = x + _row(attn, bp["proj"])
    h = layer_norm(x, bp["ln2"])
    h = jax.nn.gelu(dense(h, bp["mlp_in"]))
    return x + _row(h, bp["mlp_out"])


def _tp_vit_forward(
    params: dict, x: jax.Array, cfg: ViTConfig, use_flash: bool = False
) -> jax.Array:
    """The ViT forward over a MODEL shard, inside shard_map: every token is
    local (no seq sharding); weights of the sharded layers are local
    slices.  Composes the same patchify/layer_norm/pool/head contract as
    models/vit.py's single-device trunk.  ``use_flash`` swaps the local
    per-head-shard attention for the fused Pallas kernel
    (ops/pallas_attention.py — head-sharded local attention is exactly
    the kernel's shape, the ulysses composition again)."""
    heads_local = cfg.heads // axis_size(MODEL_AXIS)
    from ..ops.pallas_attention import select_attention

    attention_fn = select_attention(use_flash)
    dt = jnp.bfloat16 if cfg.bf16 else x.dtype
    patches = patchify(x, cfg).astype(dt)
    tokens = dense(patches, params["embed"]) + params["pos_embed"].astype(dt)
    for i in range(cfg.depth):
        tokens = _tp_block(
            params["blocks"][str(i)], tokens, cfg, heads_local,
            attention_fn=attention_fn,
        )
    tokens = layer_norm(tokens, params["ln_f"])
    pooled = tokens.astype(jnp.float32).mean(axis=1)
    return tokens_to_logp(params, pooled)


def make_vit_tp_train_step(
    mesh: Mesh, cfg: ViTConfig, rho: float = 0.9, eps: float = 1e-6,
    use_flash: bool = False,
):
    """Build the jitted 2-D (data x model) ViT train step.

    ``step_fn(state, x, y, w, lr) -> (state, losses)`` with ``state``
    sharded per ``vit_tp_state_specs``, ``x/y/w`` sharded over ``data``,
    ``losses`` one local loss per data shard.  Grad semantics as in
    parallel/tp.py: VMA-inserted psums deliver the data-axis SUM of
    local-mean grads (and the model-axis reduction for replicated params);
    divide by the data degree for DDP mean semantics.
    """
    _check_head_divisibility(cfg, mesh)
    num_data = mesh.shape[DATA_AXIS]
    state_specs = vit_tp_state_specs(cfg)

    def local_step(state: TrainState, x, y, w, lr):
        def loss_fn(params):
            logp = _tp_vit_forward(params, x, cfg, use_flash=use_flash)
            return nll_loss(logp, y, w, reduction="mean")

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        grads = jax.tree.map(lambda g: g / num_data, grads)
        params, opt = adadelta_update(
            state.params, grads, state.opt, lr, rho, eps
        )
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_specs, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(state_specs, P(DATA_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_vit_tp_predict_step(
    mesh: Mesh, cfg: ViTConfig, use_flash: bool = False
):
    """Build the jitted ViT-TP forward for the serving path.

    ``predict_fn(params, x) -> log_probs`` with ``params`` sharded per
    ``vit_tp_param_specs`` and ``x``/the output sharded over ``data``
    (size 1 on a pure-TP serving replica, so every model shard holds the
    full batch and contributes its heads/MLP features through the two
    per-block psums)."""
    _check_head_divisibility(cfg, mesh)

    def local_predict(params, x):
        return _tp_vit_forward(params, x, cfg, use_flash=use_flash)

    sharded = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(vit_tp_param_specs(cfg), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
    )
    return jax.jit(sharded)


def make_vit_tp_eval_step(mesh: Mesh, cfg: ViTConfig, use_flash: bool = False):
    """Jitted (data x model) eval step: TP forward + the psum'd
    (loss_sum, correct) totals every eval path in the framework shares —
    params stay model-sharded through evaluation."""
    _check_head_divisibility(cfg, mesh)

    def local_eval(params, x, y, w):
        logp = _tp_vit_forward(params, x, cfg, use_flash=use_flash)
        loss_sum = nll_loss(logp, y, w, reduction="sum")
        correct = ((jnp.argmax(logp, axis=1) == y) * w).sum()
        return jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(
            vit_tp_param_specs(cfg),
            P(DATA_AXIS),
            P(DATA_AXIS),
            P(DATA_AXIS),
        ),
        out_specs=P(),
    )
    return jax.jit(sharded)
