"""Fused device-resident epoch execution — the TPU-first fast path.

The reference's hot loop pays a host round trip per batch: worker-process
batch assembly, pinned-buffer H2D copy, kernel launches, and a
``loss.item()`` sync (reference mnist_ddp.py:67-79; SURVEY.md §3.2).  At
MNIST scale that host traffic, not compute, dominates wall clock — the
~12 ms/step budget of the README table (SURVEY.md §7 'hard parts').

The TPU-native shape eliminates the per-step host boundary entirely:

- The raw uint8 dataset lives in HBM, replicated (60k x 28 x 28 = 47 MB).
- Each epoch is ONE jitted call: ``lax.scan`` over the steps; each step
  gathers its batch by index, normalizes on-device (VPU), and runs the
  full train step (forward, loss, backward, gradient ``pmean`` over the
  ``data`` axis, Adadelta update) without leaving the chip.
- The epoch permutation is computed on-device from the shuffle key folded
  with the epoch number — same semantics as the host sampler
  (fresh epoch-seeded permutation, pad-to-divisible by repeating leading
  indices; parallel/sampler.py), different generator.
- Per-step first-replica losses come back as ONE array per epoch, so the
  reference's train log lines can still be printed verbatim (from host,
  after the epoch) with zero mid-epoch syncs.

Eval is fused the same way: scan over test batches accumulating
(loss_sum, correct) with a padding mask, one psum at the end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..data.transforms import MNIST_MEAN, MNIST_STD
from ..models.net import Net
from ..ops.loss import nll_loss
from ..ops.pallas_adadelta import adadelta_update_best
from .ddp import TrainState, eval_variables
from .mesh import DATA_AXIS
from ..utils.jax_compat import shard_map


def _normalize_dev(x_u8: jax.Array, compute_dtype) -> jax.Array:
    """On-device ToTensor + Normalize (uint8 NHW -> float NHWC 1-channel),
    same affine scale/shift form as data/transforms.py:normalize."""
    scale = jnp.float32(1.0 / (255.0 * MNIST_STD))
    shift = jnp.float32(-MNIST_MEAN / MNIST_STD)
    x = x_u8.astype(jnp.float32) * scale + shift
    return x[..., None].astype(compute_dtype)


def device_put_dataset(images, labels, mesh: Mesh):
    """Stage the raw uint8 dataset replicated in HBM (one transfer per
    run, amortized over every epoch).  Replication itself — including the
    multi-controller path — lives in ddp.replicate_params."""
    import numpy as np

    from .ddp import replicate_params

    return replicate_params(
        (np.asarray(images), np.asarray(labels, dtype=np.int32)), mesh
    )


def _epoch_scan_builder(
    dataset_size: int,
    global_batch: int,
    n_shards: int,
    compute_dtype,
    step_fn,
    pregather: bool = False,
):
    """The family-agnostic fused-epoch skeleton: epoch-seeded permutation
    with wrap-fill masking, per-shard batch slicing + on-device normalize,
    one ``lax.scan`` over the steps.  ``step_fn(state, x, y, w, shard,
    dropout_key, lr) -> (state, loss)`` is the family-specific body
    (forward + grads + update); fused_vit.py injects the ViT's.  Shared so
    the sampling/masking semantics cannot diverge between families.
    Returns ``(local_epoch, num_batches)``.

    ``pregather``: materialize the whole permuted epoch ONCE up front
    (one big gather, +47 MB transient uint8 HBM at MNIST scale) and slice
    each step's batch contiguously, instead of gathering 200 random rows
    per step.  Identical rows in identical order — bit-identical batches
    and losses (tests/test_fused.py pins it) — only the input-path HLO
    differs.  Off by default until the hardware step-attribution ladder
    (tools/step_attr_bench.py) shows which input path wins; measured by
    ``bench.py --pregather``."""
    if global_batch % n_shards:
        raise ValueError(f"global batch {global_batch} not divisible by mesh")
    shard_batch = global_batch // n_shards
    num_batches = -(-dataset_size // global_batch)
    padded = num_batches * global_batch

    def local_epoch(state, images, labels, epoch, shuffle_key, dropout_key, lr):
        # Epoch-seeded permutation; wrap to fill the final batch, with the
        # wrapped filler masked out (weight 0) like the host loader's
        # final-batch padding.
        perm = jax.random.permutation(
            jax.random.fold_in(shuffle_key, epoch), dataset_size
        )
        positions = jnp.arange(padded)
        perm = jnp.take(perm, positions % dataset_size)
        valid = (positions < dataset_size).astype(jnp.float32)
        shard = jax.lax.axis_index(DATA_AXIS)

        if pregather:
            ep_x = jnp.take(images, perm, axis=0)
            ep_y = jnp.take(labels, perm, axis=0)

            def one_step(state, batch):
                step, step_valid = batch
                start = step * global_batch + shard * shard_batch
                w = jax.lax.dynamic_slice_in_dim(
                    step_valid, shard * shard_batch, shard_batch
                )
                x = _normalize_dev(
                    jax.lax.dynamic_slice_in_dim(ep_x, start, shard_batch),
                    compute_dtype,
                )
                y = jax.lax.dynamic_slice_in_dim(ep_y, start, shard_batch)
                return step_fn(state, x, y, w, shard, dropout_key, lr)

            xs = (
                jnp.arange(num_batches),
                valid.reshape(num_batches, global_batch),
            )
        else:

            def one_step(state, batch):
                step_perm, step_valid = batch  # [global_batch] each
                idx = jax.lax.dynamic_slice_in_dim(
                    step_perm, shard * shard_batch, shard_batch
                )
                w = jax.lax.dynamic_slice_in_dim(
                    step_valid, shard * shard_batch, shard_batch
                )
                x = _normalize_dev(jnp.take(images, idx, axis=0), compute_dtype)
                y = jnp.take(labels, idx, axis=0)
                return step_fn(state, x, y, w, shard, dropout_key, lr)

            xs = (
                perm.reshape(num_batches, global_batch),
                valid.reshape(num_batches, global_batch),
            )

        state, losses = jax.lax.scan(one_step, state, xs)
        return state, losses

    return local_epoch, num_batches


def _local_epoch_builder(
    model: Net,
    dataset_size: int,
    global_batch: int,
    n_shards: int,
    compute_dtype,
    rho: float,
    eps: float,
    dropout: bool,
    use_pallas: bool | None,
    use_bn: bool = False,
    pregather: bool = False,
    zero: bool = False,
):
    """The CNN family's fused-epoch body on the shared skeleton: returns
    ``local_epoch(state, images, labels, epoch, shuffle_key, dropout_key,
    lr) -> (state, losses[num_batches])`` (per-shard, to be run inside
    ``shard_map``) plus ``num_batches``.

    ``use_bn``: the scan carry's ``state.batch_stats`` threads the BN
    running averages through every step; batch statistics psum over the
    data axis inside the forward and the wrap-filler rows (weight 0) are
    mask-excluded, exactly like the per-batch step (parallel/ddp.py).

    ``zero``: ZeRO-1 optimizer sharding (parallel/zero.py) inside the
    fused scan — the carry's ``state.opt`` is each shard's LOCAL 1/N flat
    accumulator slice, and the update runs zero_update's
    psum_scatter -> shard-local Adadelta -> all_gather instead of
    pmean + replicated update.  Same dropout-stream folding as the
    per-batch steps (step, then shard), so fused-ZeRO trajectories are
    bit-comparable to per-batch ZeRO's."""
    if zero:
        from .zero import zero_update

    def step_fn(state: TrainState, x, y, w, shard, dropout_key, lr):
        key = jax.random.fold_in(dropout_key, state.step)
        key = jax.random.fold_in(key, shard)

        def loss_fn(params):
            if use_bn:
                logp, mutated = model.apply(
                    {"params": params, "batch_stats": state.batch_stats},
                    x, train=True, dropout=dropout, mask=w,
                    rngs={"dropout": key}, mutable=["batch_stats"],
                )
                new_stats = mutated["batch_stats"]
            else:
                logp = model.apply(
                    {"params": params}, x, train=dropout,
                    rngs={"dropout": key},
                )
                new_stats = state.batch_stats
            return nll_loss(logp, y, w, reduction="mean"), new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        if zero:
            # zero_update's psum_scatter consumes the RAW local grads (the
            # /N that makes DDP's mean happens on the scattered shard).
            params, opt = zero_update(
                state.params, grads, state.opt, lr, n_shards, rho, eps
            )
        else:
            grads = jax.lax.pmean(grads, DATA_AXIS)
            params, opt = adadelta_update_best(
                state.params, grads, state.opt, lr, rho, eps,
                use_pallas=use_pallas,
            )
        return TrainState(params, opt, state.step + 1, new_stats), loss

    return _epoch_scan_builder(
        dataset_size, global_batch, n_shards, compute_dtype, step_fn,
        pregather=pregather,
    )


def make_fused_train_epoch(
    mesh: Mesh,
    dataset_size: int,
    global_batch: int,
    compute_dtype=jnp.float32,
    rho: float = 0.9,
    eps: float = 1e-6,
    dropout: bool = True,
    use_pallas: bool | None = None,
):
    """Build ``epoch_fn(state, images, labels, epoch, shuffle_key,
    dropout_key, lr) -> (state, losses[num_batches, n_shards])``.

    ``num_batches = ceil(dataset_size / global_batch)``; a non-divisible
    final batch is filled by wrapping the permutation and the filler
    samples carry weight 0 — exactly the host loader's final-batch padding
    (data/loader.py), so both paths train on the same effective samples.
    """
    model = Net(compute_dtype=compute_dtype)
    n_shards = mesh.shape[DATA_AXIS]
    local_epoch, num_batches = _local_epoch_builder(
        model, dataset_size, global_batch, n_shards,
        compute_dtype, rho, eps, dropout, use_pallas,
    )

    def local_epoch_col(*a):
        state, losses = local_epoch(*a)
        return state, losses[:, None]  # per-shard loss column

    sharded = shard_map(
        local_epoch_col,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P(None, DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,)), num_batches


def _eval_scan_builder(
    dataset_size: int,
    global_batch: int,
    n_shards: int,
    compute_dtype,
    predict,
):
    """The family-agnostic fused-eval skeleton: scan over wrap-padded
    batches accumulating masked (loss_sum, correct), one psum at the end.
    ``predict(params, x) -> logp`` is the family-specific forward;
    fused_vit.py injects the ViT's.  Returns ``local_eval(params, images,
    labels) -> psum'd [loss_sum, correct]`` for use inside shard_map."""
    if global_batch % n_shards:
        raise ValueError(f"global batch {global_batch} not divisible by mesh")
    shard_batch = global_batch // n_shards
    num_batches = -(-dataset_size // global_batch)
    padded = num_batches * global_batch

    def local_eval(params, images, labels):
        idx = jnp.arange(padded) % dataset_size  # wrap; wrapped tail masked below
        valid = (jnp.arange(padded) < dataset_size).astype(jnp.float32)
        shard = jax.lax.axis_index(DATA_AXIS)

        def one_batch(carry, batch):
            loss_sum, correct = carry
            b_idx, b_valid = batch
            i = jax.lax.dynamic_slice_in_dim(b_idx, shard * shard_batch, shard_batch)
            v = jax.lax.dynamic_slice_in_dim(b_valid, shard * shard_batch, shard_batch)
            x = _normalize_dev(jnp.take(images, i, axis=0), compute_dtype)
            y = jnp.take(labels, i, axis=0)
            logp = predict(params, x)
            loss_sum += nll_loss(logp, y, v, reduction="sum")
            correct += ((jnp.argmax(logp, axis=1) == y) * v).sum()
            return (loss_sum, correct), None

        (loss_sum, correct), _ = jax.lax.scan(
            one_batch,
            (jnp.float32(0.0), jnp.float32(0.0)),
            (
                idx.reshape(num_batches, global_batch),
                valid.reshape(num_batches, global_batch),
            ),
        )
        return jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)

    return local_eval


def _local_eval_builder(
    model: Net,
    dataset_size: int,
    global_batch: int,
    n_shards: int,
    compute_dtype,
    use_bn: bool = False,
):
    """The CNN family's fused-eval body on the shared skeleton.  With
    ``use_bn``, ``params`` is the full variable dict (running averages
    normalize, torch ``model.eval()`` semantics)."""
    variables_of = (lambda p: p) if use_bn else (lambda p: {"params": p})

    def predict(params, x):
        return model.apply(variables_of(params), x, train=False)

    return _eval_scan_builder(
        dataset_size, global_batch, n_shards, compute_dtype, predict
    )


def make_fused_eval(
    mesh: Mesh,
    dataset_size: int,
    global_batch: int,
    compute_dtype=jnp.float32,
):
    """Build ``eval_fn(params, images, labels) -> (loss_sum, correct)``
    over the whole test set in one device call (scan over batches, padding
    masked, single psum) — the fused form of parallel/ddp.py:make_eval_step."""
    model = Net(compute_dtype=compute_dtype)
    n_shards = mesh.shape[DATA_AXIS]
    local_eval = _local_eval_builder(
        model, dataset_size, global_batch, n_shards, compute_dtype
    )

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_fused_run(
    mesh: Mesh,
    train_size: int,
    test_size: int,
    global_batch: int,
    eval_batch: int,
    epochs: int,
    compute_dtype=jnp.float32,
    rho: float = 0.9,
    eps: float = 1e-6,
    dropout: bool = True,
    use_pallas: bool | None = None,
    from_key: bool = False,
    use_bn: bool = False,
    start_epoch: int = 1,
    pregather: bool = False,
    conv_impl: str = "conv",
    zero: bool = False,
):
    """Whole-run fusion: EVERY epoch's training scan plus its full-test-set
    eval as ONE jitted device call.

    ``zero`` composes ZeRO-1 optimizer sharding (parallel/zero.py) into
    the fused program (round-4 verdict item 5): ``state.opt`` is the flat
    sharded :class:`~..parallel.zero.ZeroAdadeltaState` (in/out specs
    ``P('data')``), the per-step update is zero_update's
    reduce-scatter/local-update/all-gather, and a ``from_key`` run creates
    the local accumulator slices inside the compiled program.  Excludes
    ``use_pallas`` (both re-lay-out the same state; one flat-layout owner
    per run, same rule as the per-batch paths).

    ``start_epoch`` (default 1 — same lowered program as always) offsets
    the scanned epoch numbers so a ``--resume-state`` continuation keeps
    the epoch-seeded shuffle stream exactly where the saved run left it.

    The reference pays a host round trip per *batch* (mnist_ddp.py:67-79);
    the per-epoch fusion above cuts that to one per epoch; this cuts it to
    one per *run* — a single trace/compile and a single dispatch+sync,
    which matters when device dispatch crosses a network tunnel.

    Returns ``(run_fn, num_batches)`` where ``run_fn(state, tr_x, tr_y,
    te_x, te_y, shuffle_key, dropout_key, lrs) -> (state,
    losses[epochs, num_batches, n_shards], evals[epochs, 2])``; ``lrs`` is
    the per-epoch learning-rate array (host-computed StepLR values, so the
    schedule is bit-identical to the per-epoch paths) and ``evals`` rows
    are the psum'd ``[loss_sum, correct]`` test totals after each epoch.

    ``from_key=True`` replaces ``run_fn``'s leading ``state`` argument with
    an ``init_key``: parameter init (models/net.py semantics, same RNG
    stream) and the Adadelta zero-state happen INSIDE the compiled program,
    so a cold process reaches the hot loop with one device dispatch total —
    no separate init program to compile/load, no parameter upload.
    """
    import math

    from ..ops.adadelta import adadelta_init as _tree_init
    from ..ops.pallas_adadelta import adadelta_init_flat, pallas_opt_active

    if zero and pallas_opt_active(use_pallas):
        raise ValueError(
            "zero and use_pallas both re-lay-out the Adadelta state; "
            "pick one"
        )
    # Same layout decision the step's update dispatch makes: the kernel's
    # persistent padded-flat accumulators iff the kernel will actually run.
    adadelta_init = (
        adadelta_init_flat if pallas_opt_active(use_pallas) else _tree_init
    )

    model = Net(
        compute_dtype=compute_dtype, use_bn=use_bn,
        bn_axis=DATA_AXIS if use_bn else None, conv_impl=conv_impl,
    )
    n_shards = mesh.shape[DATA_AXIS]
    if zero:
        from .zero import ZeroAdadeltaState, zero_chunk, zero_state_spec
    if zero and from_key:
        # Static per-shard accumulator length for the in-program init,
        # from the param shapes alone (eval_shape touches no device).
        shapes = jax.eval_shape(
            lambda k: model.init(
                {"params": k}, jnp.zeros((1, 28, 28, 1), jnp.float32),
                train=False,
            ),
            jax.random.PRNGKey(0),
        )
        n_params = sum(
            math.prod(s.shape) for s in jax.tree.leaves(shapes["params"])
        )
        zero_chunk_len = zero_chunk(n_params, n_shards)
    local_epoch, num_batches = _local_epoch_builder(
        model, train_size, global_batch, n_shards,
        compute_dtype, rho, eps, dropout, use_pallas, use_bn=use_bn,
        pregather=pregather, zero=zero,
    )
    local_eval = _local_eval_builder(
        model, test_size, eval_batch, n_shards, compute_dtype, use_bn=use_bn
    )

    def local_run(state, tr_x, tr_y, te_x, te_y, shuffle_key, dropout_key, lrs):
        if from_key:
            # ``state`` is the init PRNG key; same stream as
            # models/net.py:init_params, so both entries are bit-identical.
            variables = model.init(
                {"params": state}, jnp.zeros((1, 28, 28, 1), jnp.float32),
                train=False,
            )
            if zero:
                # This shard's LOCAL 1/N accumulator slice (the shard_map
                # out-spec P('data') reassembles the global flat vector).
                opt0 = ZeroAdadeltaState(
                    square_avg=jnp.zeros((zero_chunk_len,), jnp.float32),
                    acc_delta=jnp.zeros((zero_chunk_len,), jnp.float32),
                )
            else:
                opt0 = adadelta_init(variables["params"])
            state = TrainState(
                variables["params"], opt0,
                jnp.int32(0), variables["batch_stats"] if use_bn else (),
            )

        def one_epoch(state, epoch_and_lr):
            epoch, lr = epoch_and_lr
            state, losses = local_epoch(
                state, tr_x, tr_y, epoch, shuffle_key, dropout_key, lr
            )
            totals = local_eval(
                eval_variables(state.params, state.batch_stats, use_bn),
                te_x, te_y,
            )
            return state, (losses, totals)

        state, (losses, evals) = jax.lax.scan(
            one_epoch, state,
            (jnp.arange(start_epoch, start_epoch + epochs), lrs),
        )
        # all_gather the per-shard loss traces so the output is fully
        # replicated: every process can then read them with a plain local
        # np.asarray — no chief-only gather program, which would diverge
        # the collective schedule in a multi-controller world.
        gathered = jax.lax.all_gather(losses, DATA_AXIS)  # [shards, E, B]
        return state, jnp.moveaxis(gathered, 0, -1), evals

    # ZeRO-1 state travels sharded: opt specs are P('data') in AND out
    # (a from_key run has no state input — the key is replicated).
    state_out_spec = zero_state_spec() if zero else P()
    state_in_spec = P() if from_key else state_out_spec
    sharded = shard_map(
        local_run,
        mesh=mesh,
        in_specs=(state_in_spec, P(), P(), P(), P(), P(), P(), P()),
        out_specs=(state_out_spec, P(), P()),
        check_vma=False,
    )
    donate = () if from_key else (0,)
    return jax.jit(sharded, donate_argnums=donate), num_batches
