"""Device mesh construction and sharding helpers.

This is the TPU-native replacement for the reference's device-binding +
backend plumbing (``torch.cuda.set_device`` + NCCL process group, reference
mnist_ddp.py:32-37; SURVEY.md N1/N2/N14).  Instead of one process per GPU
with rank-indexed device pinning, a JAX process addresses every local chip
and parallelism is expressed as shardings over a named
``jax.sharding.Mesh``:

- axis ``'data'``  — data parallelism (the reference's whole capability)
- axis ``'model'`` — tensor/model parallelism (kept available so the mesh
  design doesn't paint us into a DP-only corner; SURVEY.md §2c)

Collectives over these axes lower to XLA ICI/DCN collectives; there is no
user-visible comm backend to select (the ``"nccl"`` hard-coding at
mnist_ddp.py:33 has no TPU analogue — SURVEY.md §5).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    num_data: int | None = None,
    num_model: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the given (default: all) devices.

    ``num_data=None`` uses every remaining device on the data axis.  The
    data axis is outermost so neighboring devices (fastest ICI links) form
    the model groups and gradient allreduce rides the longer rings.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        if len(devices) % num_model:
            raise ValueError(
                f"{len(devices)} devices not divisible by model={num_model}"
            )
        num_data = len(devices) // num_model
    need = num_data * num_model
    if need > len(devices):
        raise ValueError(
            f"requested {num_data}x{num_model} mesh but only "
            f"{len(devices)} devices are available"
        )
    grid = np.asarray(devices[:need]).reshape(num_data, num_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-leading sharding for input arrays: split dim 0 over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params/opt state under pure DP)."""
    return NamedSharding(mesh, P())
