"""Device mesh construction and sharding helpers.

This is the TPU-native replacement for the reference's device-binding +
backend plumbing (``torch.cuda.set_device`` + NCCL process group, reference
mnist_ddp.py:32-37; SURVEY.md N1/N2/N14).  Instead of one process per GPU
with rank-indexed device pinning, a JAX process addresses every local chip
and parallelism is expressed as shardings over a named
``jax.sharding.Mesh``:

- axis ``'data'``  — data parallelism (the reference's whole capability)
- axis ``'model'`` — tensor/model parallelism (kept available so the mesh
  design doesn't paint us into a DP-only corner; SURVEY.md §2c)

Collectives over these axes lower to XLA ICI/DCN collectives; there is no
user-visible comm backend to select (the ``"nccl"`` hard-coding at
mnist_ddp.py:33 has no TPU analogue — SURVEY.md §5).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_nd_mesh(
    num_data: int | None,
    minors: Sequence[tuple[str, int]],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Shared builder for every ``(data, *minors)`` mesh in the framework
    (model/tp, seq/sp, and the 3-D seq x model composition).
    ``num_data=None`` uses every remaining device on the data axis.  The
    data axis is outermost and later minors are innermost, so neighboring
    devices (fastest ICI links) form the innermost-axis groups — model
    shards ride the adjacent hops, seq rings the next-nearest, gradient
    allreduce the longest rings."""
    devices = list(devices if devices is not None else jax.devices())
    names = [name for name, _ in minors]
    sizes = [size for _, size in minors]
    minor = 1
    for size in sizes:
        minor *= size
    if num_data is None:
        if len(devices) % minor:
            raise ValueError(
                f"{len(devices)} devices not divisible by "
                + "*".join(f"{n}={s}" for n, s in minors)
            )
        num_data = len(devices) // minor
    need = num_data * minor
    if need > len(devices):
        shape = "x".join(str(s) for s in (num_data, *sizes))
        raise ValueError(
            f"requested {shape} mesh but only "
            f"{len(devices)} devices are available"
        )
    grid = np.asarray(devices[:need]).reshape(num_data, *sizes)
    return Mesh(grid, (DATA_AXIS, *names))


def make_2d_mesh(
    num_data: int | None,
    num_minor: int,
    minor_axis: str,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """The ``(data, <minor>)`` special case of :func:`make_nd_mesh`."""
    return make_nd_mesh(num_data, [(minor_axis, num_minor)], devices)


def make_mesh(
    num_data: int | None = None,
    num_model: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the standard ``(data, model)`` mesh (see ``make_2d_mesh``)."""
    return make_2d_mesh(num_data, num_model, MODEL_AXIS, devices)


def place_tree(tree, specs, mesh: Mesh):
    """Place a host-side pytree onto ``mesh`` with per-leaf PartitionSpecs.

    Single-controller worlds ``device_put`` each leaf.  Multi-controller
    worlds can't place onto non-addressable devices; there, every process
    holds the full (identical, same-PRNG) value — the DP replication story
    of ``ddp.replicate_params`` — and each contributes its addressable
    shards via ``make_array_from_callback``, which slices the local piece
    per shard index.  Shard-identical state by construction, no broadcast.
    Shared by every sharded-state layout (parallel/tp.py, ep.py, tp_vit.py).
    """
    if all(d.process_index == jax.process_index() for d in mesh.devices.flat):
        return jax.tree.map(
            lambda v, spec: jax.device_put(v, NamedSharding(mesh, spec)),
            tree,
            specs,
        )

    def place(v, spec):
        host = np.asarray(v)
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx, host=host: host[idx]
        )

    return jax.tree.map(place, tree, specs)


def local_devices() -> list[jax.Device]:
    """Every device addressable from this process — the replica-pool
    enumeration surface (serving/pool.py): one serving replica per entry.
    Process-local by construction, since a replica's engine must be able
    to ``device_put`` onto its device."""
    return list(jax.local_devices())


def replica_devices(
    n: int | None = None, devices: Sequence[jax.Device] | None = None
) -> list[jax.Device]:
    """Device assignment for an ``n``-replica pool.

    ``n=None`` means one replica per visible local device.  ``n`` beyond
    the device count wraps round-robin — replicas then share devices,
    which oversubscribes real hardware but keeps pool mechanics testable
    on single-device hosts (the wrap is the caller's explicit choice of
    ``n``, never a silent default).
    """
    pool = list(devices if devices is not None else local_devices())
    if not pool:
        raise ValueError("no devices visible to this process")
    if n is None:
        return pool
    if n < 1:
        raise ValueError(f"need >= 1 replica, got {n}")
    return [pool[i % len(pool)] for i in range(n)]


def single_device_mesh(device: jax.Device) -> Mesh:
    """The 1x1 ``(data, model)`` mesh pinning one replica to ``device``.

    Shape-compatible with :func:`make_mesh`, so every mesh consumer
    (``make_predict_step`` sharding, ``replicate_params`` placement,
    bucket validation against the data-axis size) works unchanged — the
    pool's per-replica engines differ from a single-engine deployment
    only in WHICH device the mesh names.
    """
    return make_mesh(num_data=1, num_model=1, devices=[device])


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-leading sharding for input arrays: split dim 0 over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params/opt state under pure DP)."""
    return NamedSharding(mesh, P())
