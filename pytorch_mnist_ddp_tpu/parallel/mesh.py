"""Device mesh construction and sharding helpers.

This is the TPU-native replacement for the reference's device-binding +
backend plumbing (``torch.cuda.set_device`` + NCCL process group, reference
mnist_ddp.py:32-37; SURVEY.md N1/N2/N14).  Instead of one process per GPU
with rank-indexed device pinning, a JAX process addresses every local chip
and parallelism is expressed as shardings over a named
``jax.sharding.Mesh``:

- axis ``'data'``  — data parallelism (the reference's whole capability)
- axis ``'model'`` — tensor/model parallelism (kept available so the mesh
  design doesn't paint us into a DP-only corner; SURVEY.md §2c)

Collectives over these axes lower to XLA ICI/DCN collectives; there is no
user-visible comm backend to select (the ``"nccl"`` hard-coding at
mnist_ddp.py:33 has no TPU analogue — SURVEY.md §5).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_2d_mesh(
    num_data: int | None,
    num_minor: int,
    minor_axis: str,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Shared builder for every ``(data, <minor>)`` mesh in the framework
    (model/tp, seq/sp).  ``num_data=None`` uses every remaining device on
    the data axis.  The data axis is outermost so neighboring devices
    (fastest ICI links) form the minor-axis groups — model shards and seq
    rings ride the adjacent hops, gradient allreduce the longer rings."""
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        if len(devices) % num_minor:
            raise ValueError(
                f"{len(devices)} devices not divisible by "
                f"{minor_axis}={num_minor}"
            )
        num_data = len(devices) // num_minor
    need = num_data * num_minor
    if need > len(devices):
        raise ValueError(
            f"requested {num_data}x{num_minor} mesh but only "
            f"{len(devices)} devices are available"
        )
    grid = np.asarray(devices[:need]).reshape(num_data, num_minor)
    return Mesh(grid, (DATA_AXIS, minor_axis))


def make_mesh(
    num_data: int | None = None,
    num_model: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the standard ``(data, model)`` mesh (see ``make_2d_mesh``)."""
    return make_2d_mesh(num_data, num_model, MODEL_AXIS, devices)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-leading sharding for input arrays: split dim 0 over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params/opt state under pure DP)."""
    return NamedSharding(mesh, P())
