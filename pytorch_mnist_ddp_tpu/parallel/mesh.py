"""Device mesh construction and sharding helpers.

This is the TPU-native replacement for the reference's device-binding +
backend plumbing (``torch.cuda.set_device`` + NCCL process group, reference
mnist_ddp.py:32-37; SURVEY.md N1/N2/N14).  Instead of one process per GPU
with rank-indexed device pinning, a JAX process addresses every local chip
and parallelism is expressed as shardings over a named
``jax.sharding.Mesh``:

- axis ``'data'``  — data parallelism (the reference's whole capability)
- axis ``'model'`` — tensor/model parallelism (kept available so the mesh
  design doesn't paint us into a DP-only corner; SURVEY.md §2c)

Collectives over these axes lower to XLA ICI/DCN collectives; there is no
user-visible comm backend to select (the ``"nccl"`` hard-coding at
mnist_ddp.py:33 has no TPU analogue — SURVEY.md §5).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_nd_mesh(
    num_data: int | None,
    minors: Sequence[tuple[str, int]],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Shared builder for every ``(data, *minors)`` mesh in the framework
    (model/tp, seq/sp, and the 3-D seq x model composition).
    ``num_data=None`` uses every remaining device on the data axis.  The
    data axis is outermost and later minors are innermost, so neighboring
    devices (fastest ICI links) form the innermost-axis groups — model
    shards ride the adjacent hops, seq rings the next-nearest, gradient
    allreduce the longest rings."""
    devices = list(devices if devices is not None else jax.devices())
    names = [name for name, _ in minors]
    sizes = [size for _, size in minors]
    minor = 1
    for size in sizes:
        minor *= size
    if num_data is None:
        if len(devices) % minor:
            raise ValueError(
                f"{len(devices)} devices not divisible by "
                + "*".join(f"{n}={s}" for n, s in minors)
            )
        num_data = len(devices) // minor
    need = num_data * minor
    if need > len(devices):
        shape = "x".join(str(s) for s in (num_data, *sizes))
        raise ValueError(
            f"requested {shape} mesh but only "
            f"{len(devices)} devices are available"
        )
    grid = np.asarray(devices[:need]).reshape(num_data, *sizes)
    return Mesh(grid, (DATA_AXIS, *names))


def make_2d_mesh(
    num_data: int | None,
    num_minor: int,
    minor_axis: str,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """The ``(data, <minor>)`` special case of :func:`make_nd_mesh`."""
    return make_nd_mesh(num_data, [(minor_axis, num_minor)], devices)


def make_mesh(
    num_data: int | None = None,
    num_model: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the standard ``(data, model)`` mesh (see ``make_2d_mesh``)."""
    return make_2d_mesh(num_data, num_model, MODEL_AXIS, devices)


def place_tree(tree, specs, mesh: Mesh):
    """Place a host-side pytree onto ``mesh`` with per-leaf PartitionSpecs.

    Single-controller worlds ``device_put`` each leaf.  Multi-controller
    worlds can't place onto non-addressable devices; there, every process
    holds the full (identical, same-PRNG) value — the DP replication story
    of ``ddp.replicate_params`` — and each contributes its addressable
    shards via ``make_array_from_callback``, which slices the local piece
    per shard index.  Shard-identical state by construction, no broadcast.
    Shared by every sharded-state layout (parallel/tp.py, ep.py, tp_vit.py).
    """
    if all(d.process_index == jax.process_index() for d in mesh.devices.flat):
        return jax.tree.map(
            lambda v, spec: jax.device_put(v, NamedSharding(mesh, spec)),
            tree,
            specs,
        )

    def place(v, spec):
        host = np.asarray(v)
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx, host=host: host[idx]
        )

    return jax.tree.map(place, tree, specs)


def local_devices() -> list[jax.Device]:
    """Every device addressable from this process — the replica-pool
    enumeration surface (serving/pool.py): one serving replica per entry.
    Process-local by construction, since a replica's engine must be able
    to ``device_put`` onto its device."""
    return list(jax.local_devices())


def replica_devices(
    n: int | None = None, devices: Sequence[jax.Device] | None = None
) -> list[jax.Device]:
    """Device assignment for an ``n``-replica pool.

    ``n=None`` means one replica per visible local device.  ``n`` beyond
    the device count wraps round-robin — replicas then share devices,
    which oversubscribes real hardware but keeps pool mechanics testable
    on single-device hosts (the wrap is the caller's explicit choice of
    ``n``, never a silent default).
    """
    pool = list(devices if devices is not None else local_devices())
    if not pool:
        raise ValueError("no devices visible to this process")
    if n is None:
        return pool
    if n < 1:
        raise ValueError(f"need >= 1 replica, got {n}")
    return [pool[i % len(pool)] for i in range(n)]


def single_device_mesh(device: jax.Device) -> Mesh:
    """The 1x1 ``(data, model)`` mesh pinning one replica to ``device``.

    Shape-compatible with :func:`make_mesh`, so every mesh consumer
    (``make_predict_step`` sharding, ``replicate_params`` placement,
    bucket validation against the data-axis size) works unchanged — the
    pool's per-replica engines differ from a single-engine deployment
    only in WHICH device the mesh names.
    """
    return make_mesh(num_data=1, num_model=1, devices=[device])


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-leading sharding for input arrays: split dim 0 over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS))


# ---------------------------------------------------------------------------
# Sharded serving replicas: one logical replica spanning a k-device mesh.
#
# The serving pool (serving/pool.py) historically placed one whole-model
# replica per 1x1 mesh ("dp").  A replica SHAPE names how one replica
# spans k devices instead:
#
#   "dp"     — 1 device, whole model (the classic pool replica)
#   "tpK"    — K-way tensor parallel CNN head (parallel/tp.py)
#   "vtpK"   — K-way tensor parallel ViT (parallel/tp_vit.py)
#   "epK"    — K-way expert parallel MoE-ViT (parallel/ep.py; EP rides
#              the data axis, so the replica mesh is (K, 1))
#   "ppK"    — K-stage pipeline CNN (parallel/pp.py; K must equal
#              pipeline.NUM_STAGES)
#
# A spec string like "tp4,dp,dp,dp,dp" describes a heterogeneous pool:
# one 4-device TP replica plus four 1-device DP replicas.

SHARD_KINDS = ("dp", "tp", "vtp", "ep", "pp")


def parse_shard_kind(spec: str) -> tuple[str, int]:
    """``"tp4"`` -> ``("tp", 4)``; bare ``"dp"`` -> ``("dp", 1)``.

    Every non-DP kind must name its device count explicitly (a 1-device
    "tp" replica is just dp with extra collectives — refuse the silent
    misconfiguration)."""
    s = str(spec).strip().lower()
    for kind in sorted(SHARD_KINDS, key=len, reverse=True):
        if s.startswith(kind):
            digits = s[len(kind):]
            if not digits:
                if kind == "dp":
                    return ("dp", 1)
                raise ValueError(
                    f"shard kind {spec!r} needs a device count (e.g. "
                    f"'{kind}4')"
                )
            if not digits.isdigit():
                break
            k = int(digits)
            if kind == "dp" and k != 1:
                raise ValueError(
                    f"a dp replica is 1 device by definition, got {spec!r}"
                    " (scale dp by adding replicas, not devices)"
                )
            if k < 1:
                raise ValueError(f"bad device count in {spec!r}")
            return (kind, k)
    raise ValueError(
        f"unknown replica shape {spec!r}; want one of "
        f"{', '.join(SHARD_KINDS)} with a device-count suffix"
    )


def parse_replica_shapes(spec) -> list[tuple[str, int]]:
    """A replica-shape plan from a comma-joined string or a sequence of
    per-replica specs: ``"tp4,dp,dp"`` -> ``[("tp", 4), ("dp", 1),
    ("dp", 1)]``."""
    if isinstance(spec, str):
        parts = [p for p in spec.split(",") if p.strip()]
    else:
        parts = list(spec)
    if not parts:
        raise ValueError("empty replica-shape spec")
    return [parse_shard_kind(p) for p in parts]


def replica_mesh(
    kind: str, k: int, devices: Sequence[jax.Device]
) -> Mesh:
    """The ``(data, model)`` mesh one replica of shape ``(kind, k)``
    dispatches on, over exactly ``k`` of ``devices``.

    TP/pipeline shards ride the ``model`` axis (a ``(1, k)`` mesh:
    the full batch is visible to every shard, which is what the
    column/row-parallel layers and the stage ring want); EP rides the
    existing ``data`` axis (a ``(k, 1)`` mesh — the standard "EP rides
    DP" deployment of parallel/ep.py), so serving batches additionally
    shard by rows across the expert devices."""
    if len(devices) < k:
        raise ValueError(
            f"replica shape {kind}{k} needs {k} devices, got {len(devices)}"
        )
    devs = list(devices[:k])
    if kind == "dp":
        return single_device_mesh(devs[0])
    if kind in ("tp", "vtp"):
        return make_mesh(num_data=1, num_model=k, devices=devs)
    if kind == "ep":
        return make_mesh(num_data=k, num_model=1, devices=devs)
    if kind == "pp":
        from .pipeline import NUM_STAGES

        if k != NUM_STAGES:
            raise ValueError(
                f"pipeline replicas are {NUM_STAGES}-stage, got pp{k}"
            )
        return make_mesh(num_data=1, num_model=k, devices=devs)
    raise ValueError(f"unknown shard kind {kind!r}")


def plan_replica_meshes(
    shapes: Sequence[tuple[str, int]],
    devices: Sequence[jax.Device] | None = None,
) -> list[tuple[str, int, Mesh]]:
    """Assign consecutive device blocks to a replica-shape plan and
    build each replica's mesh: ``[(kind, k, mesh), ...]``.

    Multi-device shapes take strictly disjoint consecutive blocks (a
    TP replica sharing chips with another replica would serialize its
    collectives — refuse it).  An all-1-device plan keeps the classic
    round-robin wrap of :func:`replica_devices`, so oversubscribed
    single-host test pools keep working."""
    pool = list(devices if devices is not None else local_devices())
    if not pool:
        raise ValueError("no devices visible to this process")
    if all(k == 1 for _, k in shapes):
        assigned = replica_devices(len(shapes), pool)
        return [
            (kind, 1, replica_mesh(kind, 1, [dev]))
            for (kind, _), dev in zip(shapes, assigned)
        ]
    need = sum(k for _, k in shapes)
    if need > len(pool):
        raise ValueError(
            f"replica plan {[f'{kind}{k}' for kind, k in shapes]} needs "
            f"{need} devices but only {len(pool)} are visible; "
            "multi-device replicas never share chips"
        )
    out: list[tuple[str, int, Mesh]] = []
    cursor = 0
    for kind, k in shapes:
        block = pool[cursor : cursor + k]
        out.append((kind, k, replica_mesh(kind, k, block)))
        cursor += k
    return out


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params/opt state under pure DP)."""
    return NamedSharding(mesh, P())
