"""Elastic gang runtime: the process-level ReplicaSupervisor (ISSUE 10).

PR 8 taught the serving fleet to quarantine/restart/eject sick REPLICAS
and PR 9 taught the trainer to survive its own preemption — but the
layer between them, the LAUNCHER, was a bare ``subprocess.call``: a
SIGTERM to it orphaned the child (silently defeating the PR-9 emergency
save), and one dead or hung rank left the survivors wedged in a
collective forever.  This module is the supervision the distributed
path was missing:

- :class:`GangSupervisor` — spawn one OS process per rank, forward
  SIGTERM/SIGINT to every rank's process group (the emergency-save
  path fires THROUGH the launcher now), monitor liveness + per-rank
  heartbeat files, and on a dead/hung rank SIGTERM the survivors with
  bounded grace (SIGKILL the deaf), then **gang-restart** the world
  under a seeded exponential-backoff restart budget — escalating to a
  clean non-zero exit (:data:`EXIT_GANG`) with ONE diagnostic when the
  budget is spent.  The state machine mirrors serving/pool.py's
  ReplicaSupervisor one level up: replica -> rank process, batcher
  abort -> grace kill, warm restart -> resume from the latest
  coordinated archive (the trainer's elastic-resume contract, below).
- :class:`RankHeartbeat` — the trainer-side writer: a throttled touch
  of ``ELASTIC_HEARTBEAT_FILE`` at each step boundary, so a rank that
  still answers ``poll()`` but stopped stepping (wedged collective,
  hung D2H) is detected by mtime age, not just process death.

The restart contract is deliberately NOT launcher-side resume
arithmetic: a restarted rank re-executes the ORIGINAL command with
``ELASTIC_RESTART_COUNT`` exported, and the trainer (trainer.py
elastic-resume) resumes from its own ``--save-state`` archive with
epochs-as-total semantics — the launcher needs zero knowledge of the
script's flag surface.  The one exception is ``--chaos``: a chaos
schedule describes the FIRST incarnation (the injected failure is the
experiment), so restarts strip it — otherwise the same deterministic
kill re-fires every incarnation and the budget burns down to a
vacuous red (:func:`strip_chaos_args`).

Telemetry flows through the standard obs surfaces: counters
``launch_restarts_total`` / ``rank_deaths_total{rank=}``, the
``rank_heartbeat_age_seconds{rank=}`` gauge, and ``rank_death`` /
``gang_restart`` / ``gang_exhausted`` JSONL events
(docs/OBSERVABILITY.md, docs/ROBUSTNESS.md).

stdlib-only, no jax import: the supervisor must keep working exactly
when the thing it supervises is the part that is broken.  The liveness
primitives themselves (heartbeat files, the seeded backoff ladder,
group-signalling) live in liveness.py (package root), shared with the
serving fleet's control plane (serving/fleet.py) — this module keeps
the rank-shaped wrappers.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from ..liveness import (
    BackoffLadder,
    Heartbeat,
    heartbeat_age_s,
    signal_process_group as _signal_proc,
)
from ..liveness import heartbeat_path as _liveness_heartbeat_path

# sysexits.h EX_UNAVAILABLE: the gang's restart budget is exhausted —
# the world cannot be (re)formed.  Sibling of EXIT_STALLED (75) and
# EXIT_ANOMALY (70) in the resilience package.
EXIT_GANG = 69

# Env contract between the launcher and its rank children.
ENV_HEARTBEAT_FILE = "ELASTIC_HEARTBEAT_FILE"
ENV_TELEMETRY_DIR = "ELASTIC_TELEMETRY_DIR"
ENV_RESTART_COUNT = "ELASTIC_RESTART_COUNT"
ENV_RDZV_TIMEOUT_S = "RDZV_TIMEOUT_S"
ENV_RDZV_ATTEMPTS = "RDZV_ATTEMPTS"

_FORWARDED_SIGNALS = (signal.SIGTERM, signal.SIGINT)


def heartbeat_path(directory: str, rank: int) -> str:
    return _liveness_heartbeat_path(directory, f"rank{int(rank)}")


class RankHeartbeat(Heartbeat):
    """Trainer-side heartbeat writer (liveness.Heartbeat with
    the rank env contract): ``beat()`` is called at every step boundary
    (resilience/runtime.py ``after_step``), throttled to one touch per
    ``interval_s``."""

    @classmethod
    def from_env(cls) -> "RankHeartbeat | None":
        """The trainer's constructor: ``ELASTIC_HEARTBEAT_FILE`` set by
        the launcher (or an operator) opts the step loop in; unset —
        the flagless path — builds nothing."""
        path = os.environ.get(ENV_HEARTBEAT_FILE)
        return cls(path) if path else None


def strip_chaos_args(argv: list[str]) -> list[str]:
    """Remove ``--chaos SPEC`` / ``--chaos-seed N`` pairs (and their
    ``=``-joined forms) from a child command line.  A chaos schedule
    describes incarnation 0 — the injected failure IS the experiment —
    so a gang restart must run clean or the same deterministic kill
    would re-fire every incarnation."""
    out: list[str] = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        if arg in ("--chaos", "--chaos-seed"):
            skip = True
            continue
        if arg.startswith("--chaos=") or arg.startswith("--chaos-seed="):
            continue
        out.append(arg)
    return out


class _RankProc:
    """One rank's live process + the supervisor's bookkeeping for it."""

    __slots__ = ("rank", "proc", "hb_path")

    def __init__(self, rank: int, proc: subprocess.Popen, hb_path: str | None):
        self.rank = rank
        self.proc = proc
        self.hb_path = hb_path


class GangSupervisor:
    """Supervise a gang of rank processes; restart the world on rank
    death under a budget (docs/ROBUSTNESS.md elastic state machine)::

        running ──rank dead/hung──▶ stopping (grace SIGTERM→SIGKILL)
           ▲                              │
           │  backoff elapsed             │ attempts > restart_budget
           └───────── restarting ◀────────┤
                                          ▼
                              exhausted (EXIT_GANG, one diagnostic)

    Parameters
    ----------
    spawn:
        ``spawn(rank, restart_count) -> subprocess.Popen`` — the child
        factory.  The launcher's spawn exports the rank env contract
        and starts each child in its own session (so the supervisor
        can signal the whole process GROUP); tests pass tiny
        ``python -c`` children.
    nprocs:
        Gang size (ranks 0..nprocs-1).
    restart_budget:
        Gang restarts before escalation.  0 = never restart: the first
        incident escalates immediately (still one diagnostic).
    backoff_base_s / backoff_max_s / backoff_jitter / seed:
        The exponential restart ladder, seeded like the serving
        supervisor's so two chaos runs schedule identically.
    grace_s:
        SIGTERM-to-SIGKILL window when stopping survivors (and when
        forwarding an operator signal) — the same bounded-grace
        contract as ``--preempt-grace-s``, one level up.
    heartbeat_dir / heartbeat_timeout_s:
        When both set, a rank whose heartbeat file exists but is older
        than the timeout is treated as hung (same incident path as
        death).  A rank that has not written its FIRST beat is startup,
        never hung — budget rendezvous + first-step compile elsewhere.
    healthy_after_s:
        A gang incarnation that survives this long resets the attempts
        ladder (the serving supervisor's healed-spell rule).
    propagate_exit:
        Transparent mode (the single-child launcher default): on a
        child's own non-zero exit with no budget, return ITS code with
        no diagnostic — the PR-9 ``128+signum`` convention must pass
        through the launcher unchanged.
    """

    def __init__(
        self,
        spawn,
        nprocs: int,
        *,
        restart_budget: int = 3,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        backoff_jitter: float = 0.25,
        seed: int = 0,
        grace_s: float = 10.0,
        heartbeat_dir: str | None = None,
        heartbeat_timeout_s: float = 0.0,
        healthy_after_s: float = 30.0,
        poll_s: float = 0.1,
        propagate_exit: bool = False,
        registry=None,
        sink=None,
    ):
        if nprocs < 1:
            raise ValueError(f"nprocs must be >= 1, got {nprocs}")
        self.spawn = spawn
        self.nprocs = int(nprocs)
        self.restart_budget = max(0, int(restart_budget))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_jitter = float(backoff_jitter)
        self.grace_s = float(grace_s)
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.healthy_after_s = float(healthy_after_s)
        self.poll_s = float(poll_s)
        self.propagate_exit = bool(propagate_exit)
        self._registry = registry
        self._sink = sink
        # Seeded: the backoff ladder must not make two chaos runs
        # diverge (liveness.py discipline).
        self._ladder = BackoffLadder(
            base_s=self.backoff_base_s, max_s=self.backoff_max_s,
            jitter=self.backoff_jitter, seed=seed,
        )
        self.attempts = 0        # restarts since the last healthy spell
        self.restarts = 0        # lifetime gang restarts
        self.recovery_s: list[float] = []
        self._procs: list[_RankProc] = []
        self._signal: int | None = None
        self._prev_handlers: dict[int, object] = {}
        self._incarnation_t = 0.0

    # -- the restart ladder --------------------------------------------------

    def backoff_s(self, attempts: int) -> float:
        """Rung ``attempts`` of the seeded exponential ladder — public
        so the determinism test can replay the schedule."""
        return self._ladder.delay_s(attempts)

    # -- signal forwarding ---------------------------------------------------

    def _handle_signal(self, signum, frame) -> None:
        if self._signal is not None:
            # Second signal: the operator means NOW (preempt.py rule) —
            # but take the gang down first: os._exit skips run()'s
            # finally, and a rank wedged in a dead collective (its own
            # session) would outlive the launcher holding devices and
            # ports, breaking the never-leave-orphans guarantee.
            self._signal_gang(signal.SIGKILL)
            os._exit(128 + signum)
        self._signal = signum
        self._signal_gang(signum)

    def install_signals(self) -> None:
        """Forward SIGTERM/SIGINT to every rank's process group — the
        satellite bugfix: a SIGTERM to the launcher must reach the
        children so PR 9's emergency save actually fires."""
        for sig in _FORWARDED_SIGNALS:
            self._prev_handlers[sig] = signal.signal(sig, self._handle_signal)

    def uninstall_signals(self) -> None:
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers.clear()

    def _signal_gang(self, signum: int) -> None:
        for rp in self._procs:
            if rp.proc.poll() is None:
                _signal_proc(rp.proc, signum)

    # -- gang lifecycle ------------------------------------------------------

    def _start_gang(self) -> None:
        self._procs = []
        for rank in range(self.nprocs):
            hb = (
                heartbeat_path(self.heartbeat_dir, rank)
                if self.heartbeat_dir
                else None
            )
            if hb is not None:
                # A stale beat from the previous incarnation must not
                # read as this incarnation's hang.
                try:
                    os.remove(hb)
                except OSError:
                    pass
            self._procs.append(_RankProc(rank, self.spawn(rank, self.restarts), hb))
        self._incarnation_t = time.monotonic()

    def _stop_gang(self) -> None:
        """Grace-kill every still-alive rank: SIGTERM (emergency-save
        window), then SIGKILL whatever is left after ``grace_s``."""
        alive = [rp for rp in self._procs if rp.proc.poll() is None]
        for rp in alive:
            _signal_proc(rp.proc, signal.SIGTERM)
        deadline = time.monotonic() + self.grace_s
        for rp in alive:
            remaining = deadline - time.monotonic()
            try:
                rp.proc.wait(timeout=max(0.05, remaining))
            except subprocess.TimeoutExpired:
                _signal_proc(rp.proc, signal.SIGKILL)
                rp.proc.wait()

    # -- health reads --------------------------------------------------------

    def _sick_rank(self) -> tuple[int, str, object] | None:
        """(rank, reason, detail) for the first dead/hung rank, else
        None.  A 0-exit is only an incident when the rest of the gang
        cannot finish without it — handled by the all-exited check in
        :meth:`run`, not here."""
        now_wall = time.time()
        for rp in self._procs:
            code = rp.proc.poll()
            if code is not None and code != 0:
                return rp.rank, "exit", code
            if (
                code is None
                and rp.hb_path is not None
                and self.heartbeat_timeout_s > 0
            ):
                age = heartbeat_age_s(rp.hb_path, now_wall)
                if self._registry is not None and age is not None:
                    self._registry.gauge(
                        "rank_heartbeat_age_seconds",
                        help="seconds since each rank's last step-boundary "
                        "heartbeat (absent ranks are still starting up)",
                        rank=rp.rank,
                    ).set(age)
                if age is not None and age > self.heartbeat_timeout_s:
                    return rp.rank, "heartbeat", age
        return None

    # -- the supervision loop ------------------------------------------------

    def run(self) -> int:
        """Blocking supervision: returns the launcher's exit code."""
        self._start_gang()
        try:
            while True:
                time.sleep(self.poll_s)
                if self._signal is not None:
                    # Operator-initiated: the children already got the
                    # signal (the handler forwarded it); give them the
                    # grace window to save, then propagate 128+signum.
                    self._stop_gang()
                    if self._sink:
                        self._sink.emit(
                            "gang_signal_exit", signum=self._signal,
                        )
                    return 128 + self._signal
                if (
                    self.attempts
                    and time.monotonic() - self._incarnation_t
                    > self.healthy_after_s
                ):
                    # Healed spell: the next incident starts a fresh
                    # ladder (serving supervisor rule).
                    self.attempts = 0
                sick = self._sick_rank()
                if sick is None:
                    codes = [rp.proc.poll() for rp in self._procs]
                    if all(c is not None for c in codes):
                        return 0  # whole gang finished clean
                    continue
                rank, reason, detail = sick
                code = self._handle_incident(rank, reason, detail)
                if code is not None:
                    return code
        finally:
            # Never leave orphans: whatever path exits, the gang dies
            # with the launcher.
            self._stop_gang()

    def _handle_incident(self, rank, reason, detail) -> int | None:
        """Stop the gang and either restart it (None) or escalate
        (exit code)."""
        down_t0 = time.monotonic()
        if self._registry is not None:
            self._registry.counter(
                "rank_deaths_total",
                help="rank processes that died or hung, by rank",
                rank=rank,
            ).inc()
        if self._sink:
            self._sink.emit(
                "rank_death",
                rank=rank,
                reason=reason,
                **(
                    {"exit_code": int(detail)}
                    if reason == "exit"
                    else {"heartbeat_age_s": round(float(detail), 3)}
                ),
            )
        self._stop_gang()
        if self.propagate_exit and reason == "exit":
            # Transparent single-child mode: the child's own exit code
            # passes through unchanged (the 128+signum pin).
            return int(detail)
        if self.attempts >= self.restart_budget:
            if self._sink:
                self._sink.emit(
                    "gang_exhausted",
                    attempts=self.attempts,
                    budget=self.restart_budget,
                    rank=rank,
                    reason=reason,
                )
            detail_txt = (
                f"exit {int(detail)}" if reason == "exit"
                else f"heartbeat silent {float(detail):.1f}s"
            )
            print(
                f"launch: gang failed: rank {rank} "
                f"{'died' if reason == 'exit' else 'hung'} ({detail_txt}) "
                f"and the restart budget ({self.restart_budget}) is "
                "exhausted; the latest coordinated --save-state archive "
                "is intact — fix the cause and relaunch to resume from it",
                file=sys.stderr,
                flush=True,
            )
            return EXIT_GANG
        backoff = self.backoff_s(self.attempts)
        self.attempts += 1
        time.sleep(backoff)
        self.restarts += 1
        if self._registry is not None:
            self._registry.counter(
                "launch_restarts_total",
                help="gang restarts performed by the supervising launcher",
            ).inc()
        self._start_gang()
        downtime = time.monotonic() - down_t0
        self.recovery_s.append(downtime)
        if self._sink:
            self._sink.emit(
                "gang_restart",
                attempt=self.attempts,
                restart_count=self.restarts,
                backoff_s=round(backoff, 3),
                downtime_s=round(downtime, 3),
                rank=rank,
                reason=reason,
            )
        return None
