"""The data-parallel train/eval steps (replaces ``DistributedDataParallel``
+ NCCL allreduce + the autograd engine surface; SURVEY.md N2/N3/N10).

Where the reference reaches gradient sync through autograd hooks firing
bucketed NCCL allreduces overlapped with backward (reference
mnist_ddp.py:172-174; SURVEY.md §3.2), the TPU-native shape is ONE function:
the whole hot loop — forward, loss, backward, gradient ``pmean`` over the
``data`` mesh axis, Adadelta update — is traced once and compiled by
XLA:TPU, which schedules the ICI collectives overlapped with the remaining
backward computation itself (latency-hiding scheduler).  ``lax.pmean`` is
exactly DDP's sum-divided-by-world semantics.

Reference-quirk decisions, deliberate (SURVEY.md §3.2-3.3):

- The returned per-step loss is the stack of *per-replica local* losses;
  callers log element 0, reproducing the reference's "rank-0 local loss,
  not allreduced" logging — and since it is returned as a device array, no
  ``loss.item()``-style sync stall exists unless the caller forces one.
- Eval is fully data-parallel with a ``psum`` of (loss_sum, correct_count)
  — same printed numbers as the reference's rank-0-only eval but without
  idling N-1 replicas (fixes the bubble noted in SURVEY.md §3.3).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.net import Net
from ..ops.adadelta import AdadeltaState, adadelta_init
from ..ops.loss import nll_loss
from ..ops.pallas_adadelta import adadelta_update_best
from .mesh import DATA_AXIS
from ..utils.jax_compat import shard_map


class TrainState(NamedTuple):
    """Replicated training state: params + Adadelta accumulators + step.

    ``batch_stats`` is the BN running-average collection when the model has
    (Sync)BatchNorm layers (``--syncbn``); the default empty tuple is a
    leafless pytree, so non-BN paths are untouched."""

    params: Any
    opt: AdadeltaState
    step: jax.Array  # int32 global step counter (drives per-step dropout keys)
    batch_stats: Any = ()


def make_train_state(
    params: Any, batch_stats: Any = (), use_pallas: bool | None = None
) -> TrainState:
    """``use_pallas`` mirrors the train step's flag: when the Pallas
    optimizer kernel will actually run (ops/pallas_adadelta.py:
    pallas_opt_active), the Adadelta accumulators are created in the
    kernel's persistent padded-flat layout so no per-step ravel exists."""
    from ..ops.pallas_adadelta import adadelta_init_flat, pallas_opt_active

    init = adadelta_init_flat if pallas_opt_active(use_pallas) else adadelta_init
    return TrainState(
        params=params, opt=init(params), step=jnp.int32(0),
        batch_stats=batch_stats,
    )


def eval_variables(params: Any, batch_stats: Any, use_bn: bool) -> Any:
    """The first argument for a ``use_bn``-built eval step: BN models
    evaluate on the full variable dict (params + running averages), others
    on bare params.  One definition so every caller assembles the same
    shape."""
    if use_bn:
        return {"params": params, "batch_stats": batch_stats}
    return params


def replicate_params(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree fully-replicated on the mesh.  Together with same-key
    init (models/net.py:init_params) this replaces DDP's rank-0 broadcast.

    Multi-controller worlds can't ``device_put`` onto non-addressable
    devices; there, every process contributes its (identical, same-PRNG)
    local copy via ``make_array_from_process_local_data`` — replica
    consistency by construction, no broadcast traffic at all."""
    import numpy as np

    sharding = NamedSharding(mesh, P())
    if all(d.process_index == jax.process_index() for d in mesh.devices.flat):
        return jax.device_put(tree, sharding)
    return jax.tree.map(
        lambda v: jax.make_array_from_process_local_data(
            sharding, np.asarray(v)
        ),
        tree,
    )


def forward_loss(
    model: Net,
    params: Any,
    batch_stats: Any,
    x,
    y,
    w,
    key,
    *,
    use_bn: bool,
    dropout: bool,
) -> tuple[jax.Array, Any]:
    """The shared per-replica loss body: forward + masked-mean NLL.

    Returns ``(loss, new_batch_stats)``; non-BN models pass
    ``batch_stats`` through untouched so the return shape is uniform.
    One definition feeds every replicated-gradient step variant
    (:func:`make_train_step`, the ZeRO-1 step in parallel/zero.py), so the
    reference's loss semantics (mnist.py:44-45) cannot drift between them.
    """
    variables = {"params": params}
    if use_bn:
        # train=True regardless of the dropout flag: BN must use
        # (and update) batch statistics whenever training, even in
        # the deterministic-dropout parity configurations.
        # mask=w: zero-padded samples of the final partial batch
        # stay out of the (psum'd) batch statistics, matching
        # torch's real-only smaller last batch.
        variables["batch_stats"] = batch_stats
        log_probs, mutated = model.apply(
            variables, x, train=True, dropout=dropout, mask=w,
            rngs={"dropout": key}, mutable=["batch_stats"],
        )
        return nll_loss(log_probs, y, w, reduction="mean"), mutated["batch_stats"]
    log_probs = model.apply(variables, x, train=dropout, rngs={"dropout": key})
    return nll_loss(log_probs, y, w, reduction="mean"), batch_stats


def fold_replica_step_key(dropout_key, step) -> jax.Array:
    """Per-step, per-replica dropout stream folded from the single root
    seed (reference semantics: one global seed; SURVEY.md N15).  Must be
    called inside ``shard_map`` (reads ``axis_index`` on the data axis);
    shared by every DP-family step so the streams are identical across
    step variants — the ZeRO-1 trajectory is bit-comparable to plain DP
    even with dropout on."""
    key = jax.random.fold_in(dropout_key, step)
    return jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))


def make_train_step(
    mesh: Mesh,
    compute_dtype: jnp.dtype = jnp.float32,
    rho: float = 0.9,
    eps: float = 1e-6,
    dropout: bool = True,
    use_pallas: bool | None = None,
    use_bn: bool = False,
    conv_impl: str = "conv",
):
    """Build the jitted DP train step.

    Returns ``step_fn(state, x, y, w, dropout_key, lr) -> (state, losses)``
    where ``x`` is the *global* batch (sharded over the ``data`` axis by the
    input pipeline), ``w`` the 0/1 padding mask, and ``losses`` a
    ``[num_data_shards]`` array of per-replica local losses.

    ``use_bn``: the model carries (Sync)BatchNorm layers — batch statistics
    come from a (sum, sq-sum, count) psum over the ``data`` axis inside the
    forward (the ``torch.nn.SyncBatchNorm`` allreduce, ridden on ICI; see
    models/net.py:SyncBatchNorm for why not a pmean of shard means),
    gradients flow through the synced stats exactly as torch's do, and the
    updated running averages (identical on every replica, since they blend
    the synced stats) travel in ``state.batch_stats``.
    """
    model = Net(
        compute_dtype=compute_dtype, use_bn=use_bn,
        bn_axis=DATA_AXIS if use_bn else None, conv_impl=conv_impl,
    )

    def local_step(state: TrainState, x, y, w, dropout_key, lr):
        key = fold_replica_step_key(dropout_key, state.step)

        def loss_fn(params):
            return forward_loss(
                model, params, state.batch_stats, x, y, w, key,
                use_bn=use_bn, dropout=dropout,
            )

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        # The DDP allreduce: mean over replicas == bucketed NCCL sum / world.
        grads = jax.lax.pmean(grads, DATA_AXIS)
        params, opt = adadelta_update_best(
            state.params, grads, state.opt, lr, rho, eps, use_pallas=use_pallas
        )
        new_state = TrainState(
            params=params, opt=opt, step=state.step + 1, batch_stats=new_stats
        )
        return new_state, loss[None]  # keep a per-shard loss axis

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=(P(), P(DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_predict_step(
    mesh: Mesh, compute_dtype: jnp.dtype = jnp.float32, use_bn: bool = False,
    conv_impl: str = "conv",
):
    """Build the jitted forward-only step for the serving path.

    Returns ``predict_fn(params, x) -> log_probs`` — per-sample ``[N, 10]``
    log-probabilities for a global batch sharded over the ``data`` axis,
    output sharded the same way (the host reads the full array once per
    dispatch).  Unlike :func:`make_eval_step` there is no label reduction:
    serving needs the per-request rows back, and padded rows are sliced
    off on the host (rows are per-sample independent through the whole
    eval-mode forward, so padding cannot perturb real rows).

    ``params`` follows :func:`eval_variables`: the full variable dict for
    BN-bearing checkpoints (eval-mode normalization by running averages),
    bare params otherwise.  One trace per input shape — the serving
    engine only ever calls this at its warmed bucket shapes, enforced by
    a RecompileSentinel (serving/engine.py).
    """
    model = Net(
        compute_dtype=compute_dtype, use_bn=use_bn, conv_impl=conv_impl
    )

    def local_predict(params, x):
        variables = params if use_bn else {"params": params}
        return model.apply(variables, x, train=False)

    sharded = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_int8_predict_step(mesh: Mesh, int8_impl: str = "dot"):
    """Build the jitted int8 forward for the serving path.

    The quantized twin of :func:`make_predict_step`: ``predict_fn
    (qparams, x) -> log_probs`` over the same data-axis sharding, where
    ``qparams`` is a :func:`~..models.quant.quantize_params` tree
    (replicated).  Same one-trace-per-bucket contract, enforced by the
    engine's per-variant RecompileSentinel; parity with the f32 forward
    is gated at warmup (serving/engine.py verify_parity), never assumed.

    ``int8_impl`` selects the dense-head implementation: ``"dot"`` is
    the reference ``lax.dot_general`` path, ``"pallas"`` the fused
    Pallas kernel (ops/pallas_infer.py) — same quantization scheme, so
    the engine's parity gate covers both.
    """
    from ..models.quant import int8_forward_fn

    fwd = int8_forward_fn(int8_impl)
    sharded = shard_map(
        fwd,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_packed_predict_step(
    mesh: Mesh, compute_dtype: jnp.dtype = jnp.float32, use_bn: bool = False,
    conv_impl: str = "conv",
):
    """Packed twin of :func:`make_predict_step` for ragged batching.

    ``predict_fn(params, x, seg_ids) -> log_probs`` where ``x`` is one
    dense ``[capacity, ...]`` rows buffer holding several requests
    back-to-back and ``seg_ids`` is the ``int32[capacity]`` segment-id
    vector (serving/buckets.py ``segment_ids``): row -> owning request,
    ``-1`` on padding rows.  Rows are per-sample independent through the
    eval-mode forward, so live rows are bit-identical to the padded
    path; padding rows are masked to exactly 0.0 on device (rather than
    whatever log_softmax of a zero row gives) so the host-side unpacker
    can assert on them cheaply.  Segment VALUES never affect compilation
    — the trace is keyed by the capacity shape alone, preserving the
    one-executable contract the packed ladder exists for.
    """
    model = Net(
        compute_dtype=compute_dtype, use_bn=use_bn, conv_impl=conv_impl
    )

    def local_predict(params, x, seg_ids):
        variables = params if use_bn else {"params": params}
        logits = model.apply(variables, x, train=False)
        return jnp.where(seg_ids[:, None] >= 0, logits, 0.0)

    sharded = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_packed_int8_predict_step(mesh: Mesh, int8_impl: str = "dot"):
    """Packed twin of :func:`make_int8_predict_step` (see
    :func:`make_packed_predict_step` for the segment contract)."""
    from ..models.quant import int8_forward_fn

    fwd = int8_forward_fn(int8_impl)

    def local_predict(qparams, x, seg_ids):
        logits = fwd(qparams, x)
        return jnp.where(seg_ids[:, None] >= 0, logits, 0.0)

    sharded = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_eval_step(
    mesh: Mesh, compute_dtype: jnp.dtype = jnp.float32, use_bn: bool = False,
    conv_impl: str = "conv",
):
    """Build the jitted distributed eval step.

    Returns ``eval_fn(params, x, y, w) -> (loss_sum, correct)`` — the
    sum-reduced NLL (reference mnist_ddp.py:97) and the argmax-match count
    (mnist_ddp.py:98-99) over the REAL (unpadded) samples of the global
    batch, psum'd over the mesh so every process holds the totals.

    With ``use_bn``, ``params`` is the full variable dict
    ``{"params": ..., "batch_stats": ...}`` and eval normalizes by the
    running averages (torch ``model.eval()`` semantics).
    """
    model = Net(
        compute_dtype=compute_dtype, use_bn=use_bn, conv_impl=conv_impl
    )

    def local_eval(params, x, y, w):
        variables = params if use_bn else {"params": params}
        log_probs = model.apply(variables, x, train=False)
        loss_sum = nll_loss(log_probs, y, w, reduction="sum")
        pred = jnp.argmax(log_probs, axis=1)
        correct = ((pred == y) * w).sum()
        # Distributed eval: one psum replaces the reference's rank-0-only
        # eval bubble (SURVEY.md §3.3), printed numbers unchanged.
        totals = jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)
        return totals

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
