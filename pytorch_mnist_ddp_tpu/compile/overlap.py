"""Startup overlap: run named startup tasks concurrently, rendezvous,
and MEASURE how much wall clock the overlap actually hid.

The trainer's startup phase used to be a serial chain — dataset H2D,
trace+compile, checkpoint restore, each waiting for the last.  This
runner executes them as named jobs on a :class:`~.service.CompileService`
and, at :meth:`rendezvous`, reports

    startup_overlap_ratio = (sum of task durations - wall) / sum

— 0.0 when the tasks effectively ran serially (or there was only one),
approaching ``1 - max/sum`` when they fully overlapped.  The ratio is a
gauge (``startup_overlap_ratio``) and rides the ``startup_overlap``
JSONL event with the per-task durations, so `tools/perf_report.py
--telemetry` can show exactly which startup leg dominated.

Stdlib-only, like the service: tasks are opaque callables.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from .service import CompileService


class StartupTasks:
    """Named concurrent startup jobs with a measuring rendezvous.

    Usage::

        tasks = StartupTasks(service)
        tasks.add("compile", program.build)  # a compile/program.py Program
        tasks.add("restore", load_checkpoint)
        tasks.result("compile")              # blocks on that task only
        tasks.rendezvous()                   # everything done; ratio recorded
    """

    def __init__(self, service: CompileService, registry=None, sink=None):
        self._service = service
        self._registry = registry
        self._sink = sink
        self._lock = threading.Lock()
        self._jobs: dict[str, Any] = {}
        self._durations: dict[str, float] = {}
        # Time each task spent blocked in result() on ANOTHER task —
        # dependency serialization, which must not count as "hidden by
        # overlap" in the ratio (a chain that ran strictly serially must
        # score ~0, per the contract above).
        self._waits: dict[str, float] = {}
        self._current = threading.local()
        self._t0 = time.perf_counter()

    def add(self, name: str, fn: Callable[[], Any], kind: str = "startup_task") -> None:
        """Start ``fn`` now, under ``name``.  ``kind`` is the span name
        the service records; pass ``kind="compile"`` for the jobs that
        should land on ``compile_seconds_total``."""
        if name in self._jobs:
            raise ValueError(f"startup task {name!r} already added")

        def timed():
            self._current.name = name
            t0 = time.perf_counter()
            try:
                return fn()
            finally:
                with self._lock:
                    self._durations[name] = time.perf_counter() - t0
                self._current.name = None

        self._jobs[name] = self._service.submit(name, timed, kind=kind)

    def result(self, name: str, timeout: float | None = None) -> Any:
        """Block on ONE task (others keep running).  Called from inside
        another task's body, the blocked time is recorded against the
        CALLER as dependency wait and excluded from the overlap ratio."""
        caller = getattr(self._current, "name", None)
        if caller is None:
            return self._jobs[name].result(timeout)
        t0 = time.perf_counter()
        try:
            return self._jobs[name].result(timeout)
        finally:
            with self._lock:
                self._waits[caller] = (
                    self._waits.get(caller, 0.0) + time.perf_counter() - t0
                )

    def duration(self, name: str) -> float | None:
        """Wall seconds ``name`` took, or None while still running.
        Includes any time the task spent waiting on another task's
        result — that wait is real startup serialization and must not
        be hidden from the attribution (the ratio, by contrast,
        excludes it)."""
        with self._lock:
            return self._durations.get(name)

    def wait_seconds(self, name: str) -> float:
        """Seconds ``name`` has spent blocked on other tasks' results —
        the serialization component :meth:`duration` includes and the
        overlap ratio excludes."""
        with self._lock:
            return self._waits.get(name, 0.0)

    def rendezvous(self, timeout: float | None = None) -> float:
        """Wait for every task; record and return the overlap ratio."""
        for job in self._jobs.values():
            job.result(timeout)
        wall = time.perf_counter() - self._t0
        with self._lock:
            durations = dict(self._durations)
            waits = dict(self._waits)
        # Effective (active) time per task: blocked-on-dependency time is
        # serialization, not concurrent work — counting it would report a
        # strictly serial restore→compile chain as a large overlap win.
        total = sum(
            max(0.0, dur - waits.get(name, 0.0))
            for name, dur in durations.items()
        )
        ratio = max(0.0, (total - wall) / total) if total > 0 else 0.0
        if self._registry is not None:
            self._registry.gauge(
                "startup_overlap_ratio",
                help="fraction of summed startup-task time hidden by overlap",
            ).set(ratio)
        if self._sink is not None:
            fields = {}
            if any(v > 0 for v in waits.values()):
                fields["waits"] = {k: round(v, 6) for k, v in waits.items()}
            self._sink.emit(
                "startup_overlap",
                wall_s=wall,
                tasks={k: round(v, 6) for k, v in durations.items()},
                overlap_ratio=ratio,
                **fields,
            )
        return ratio
