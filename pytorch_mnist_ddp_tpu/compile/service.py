"""Background compile service: a small thread pool for `(trace, lower,
compile)` work, so startup programs build CONCURRENTLY instead of one at
a time.

Why threads work here: XLA compilation releases the GIL for the long
middle of the job (the C++ compiler), and jax's dispatch/trace caches
are thread-safe, so N independent programs — the fused train run, the
DDP step, every serving bucket — compile in parallel on a multi-core
host while the main thread keeps doing startup work (dataset H2D,
checkpoint restore).  Tracing itself is Python-under-GIL, but it is the
short prefix of each job; the wall-clock win is the compile overlap, and
the structural test pins it with a GIL-releasing fake compiler
(tests/test_compile.py).

This module is deliberately jax-free (stdlib only): jobs are opaque
callables, so the fake-compiler tests exercise the real scheduling
machinery, and importing the service never pays a device-init cost —
the same contract as obs/ and analysis/engine.py.

Every job is timed and reported:

- ``compile_seconds_total{fn=<name>}`` — registry counter accumulating
  wall seconds per named program (the CI startup smoke asserts this
  DROPS between a cold and a warm run);
- a ``compile`` span (obs/spans) with the job name as the ``fn`` field,
  so JSONL telemetry reconstructs what compiled when, and for how long.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable

from ..obs.spans import span


class CompileJob:
    """Handle to one submitted job; ``result()`` blocks and re-raises."""

    __slots__ = ("name", "_future")

    def __init__(self, name: str, future: Future):
        self.name = name
        self._future = future

    def result(self, timeout: float | None = None) -> Any:
        return self._future.result(timeout)

    def done(self) -> bool:
        return self._future.done()


class CompileService:
    """Run compile jobs off the main thread, several at a time.

    Parameters
    ----------
    max_workers:
        Concurrent jobs; defaults to ``min(8, cpu_count)``.  Compilation
        is CPU-bound in the XLA backend, so more workers than cores only
        adds contention.
    registry:
        Optional obs registry: each job's wall time lands on
        ``compile_seconds_total{fn=name}``.
    sink:
        Optional obs event sink: each job runs inside a ``compile`` span
        (start/end JSONL events carrying ``fn=name``).

    Thread-safety contract for jax jobs: concurrent ``jit`` calls (and
    ``lower().compile()``) with DISTINCT signatures are safe and compile
    in parallel; submitting the same (fn, shape) twice concurrently is
    merely wasteful, not wrong (jax dedupes on its own cache).  The
    service never imports jax — callers close over it.
    """

    def __init__(self, max_workers: int | None = None, registry=None, sink=None):
        if max_workers is None:
            import os

            max_workers = min(8, max(2, os.cpu_count() or 1))
        if max_workers < 1:
            raise ValueError(f"max_workers must be >= 1, got {max_workers}")
        self.max_workers = max_workers
        self._registry = registry
        self._sink = sink
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="compile"
        )
        self._lock = threading.Lock()
        self._jobs: list[CompileJob] = []

    # -- submission -----------------------------------------------------------

    def submit(
        self,
        name: str,
        fn: Callable[..., Any],
        *args,
        kind: str = "compile",
        **kwargs,
    ) -> CompileJob:
        """Queue ``fn(*args, **kwargs)`` under the label ``name``.

        The label is the telemetry identity (``compile_seconds_total{fn=
        name}``, the span's ``fn`` field); keep it stable across runs so
        cold/warm comparisons line up.  ``kind`` is the span name and
        defaults to ``compile``; non-compile startup work sharing the
        pool (checkpoint restore, H2D rendezvous) passes e.g.
        ``kind="startup_task"`` so it never pollutes the compile
        counter.
        """

        def run():
            import time

            t0 = time.perf_counter()
            with span(kind, sink=self._sink, registry=self._registry,
                      fn=name):
                out = fn(*args, **kwargs)
            if kind == "compile" and self._registry is not None:
                self._registry.counter(
                    "compile_seconds_total",
                    help="wall seconds spent building executables, per program",
                    fn=name,
                ).inc(time.perf_counter() - t0)
            return out

        job = CompileJob(name, self._pool.submit(run))
        with self._lock:
            self._jobs.append(job)
        return job

    # -- rendezvous -----------------------------------------------------------

    def wait_all(self, timeout: float | None = None) -> list[Any]:
        """Block until every job submitted so far finishes; results in
        submission order.  The first job error re-raises here (later
        jobs still run to completion — the pool is not cancelled, so a
        failed startup reports the FIRST cause, not a cascade)."""
        with self._lock:
            jobs = list(self._jobs)
        return [j.result(timeout) for j in jobs]

    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait)

    def __enter__(self) -> "CompileService":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(wait=True)
