"""The unified ``Program`` artifact: ONE compile/AOT/dispatch path for
the trainer, the serving engine/pool, and the bench tools.

Before this module the repo hand-rolled trace → lower → compile → AOT
key → sentinel budget → telemetry in four places (trainer fused startup,
serving warmup, supervisor warm-restart, bench tools), each with its own
key composition and dispatch idiom.  A :class:`Program` bundles all of
it:

- the **jit fn** (donation spec and shardings are baked in at
  ``jax.jit`` time — ``donate_argnums``, ``shard_map`` specs);
- the **abstract args** (``jax.ShapeDtypeStruct`` with shardings, or
  concrete examples) that fix the one signature the program serves;
- the **AOT key config** — the dict the
  :class:`~.aot.ExecutableStore` digests together with the package
  source and environment, composed by ONE function per program family
  (:func:`predict_config`) so two surfaces that mean the same program
  produce the same digest and the second surface starts as a pure
  deserialize (cross-surface reuse, docs/COMPILE.md);
- the **recompile budget** — an optional shared
  :class:`~..analysis.sentinel.RecompileSentinel` that guards jit-mode
  dispatch exactly as before (budgets unchanged: warm-mode builds
  produce the same trace counts the old ladders did);
- the **compile span / telemetry identity** — ``Program.name`` is the
  label on ``compile_seconds_total{fn=}``, the ``compile`` span, and
  the ``aot_executable`` events, whichever surface builds it.

Dispatch is the slimmed steady-state path: after :meth:`Program.build`,
:attr:`Program.call` is bound to the compiled executable's C++ fast
path — zero Python wrapper frames, the same per-call host overhead as a
direct ``jax.jit`` call (pinned structurally in tests/test_program.py).

Three build modes, chosen by what the Program was constructed with:

==========  =============================  ================================
mode        chosen when                    ``build()`` does
==========  =============================  ================================
``store``   ``store`` given                ``store.load_or_compile`` (hit =
                                           zero traces); binds executable
``aot``     no store, no sentinel          ``jit_fn.lower(*args).compile()``;
                                           binds executable
``warm``    sentinel, no store             calls the sentinel once with the
                                           example args (one trace, counted
                                           against the budget); dispatch
                                           stays on the sentinel wrapper
==========  =============================  ================================

An UNBUILT Program dispatches through the sentinel (or the raw jit fn)
— exactly the lazy compile-on-first-call behavior the per-batch trainer
had before this module, so wrapping a step in a Program is always
behavior-preserving until someone builds it.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

# NOTE: jax is imported lazily inside methods — Programs are constructed
# by stdlib-only tools (tools/step_attr_bench.py exports RUNG_NAMES for
# the window-promotion rule without paying a jax import).


def compiled_fastpath(compiled) -> Callable[..., Any]:
    """Bind a ``jax.stages.Compiled`` to its C++ fast-path callable.

    ``Compiled.__call__`` creates this callable lazily on the first
    invocation and then delegates to it forever; binding it eagerly
    removes the one Python wrapper frame from every steady-state call —
    measured on the pinned jaxlib, ``Program.call`` then costs the same
    as a direct jit call (0 Python frames).  Falls back to the Compiled
    object itself if the internals move under a future jax.
    """
    try:
        call = compiled._executable.create_cpp_call(
            compiled._no_kwargs, compiled.in_tree, compiled.out_tree
        )
        if call is not None:
            return call
    except AttributeError:
        pass
    return compiled


class Program:
    """One compiled-program artifact (module docstring for the contract).

    Parameters
    ----------
    name:
        Telemetry identity: the ``compile_seconds_total{fn=}`` label,
        the ``compile`` span's ``fn`` field, the ``aot_executable``
        event name.  Keep it stable across runs so cold/warm and
        cross-surface comparisons line up.
    jit_fn:
        The ``jax.jit`` callable (donation and shardings baked in).
    example_args:
        Tuple of args fixing the signature — ``jax.ShapeDtypeStruct``
        (with shardings) and/or concrete arrays; or a zero-arg callable
        returning that tuple, evaluated at build time (for args that
        only exist after another startup task, e.g. a restored
        checkpoint).  Warm mode calls the fn with them, so there they
        must be concrete.
    config:
        The AOT key config dict (with ``store``).  Compose it through
        the canonical helper of the program family
        (:func:`predict_config` for the serving forward) — digests only
        match across surfaces when the composition is shared.
    store:
        Optional :class:`~.aot.ExecutableStore`; build becomes
        ``load_or_compile`` and a warm start deserializes (zero traces).
    sentinel:
        Optional shared :class:`RecompileSentinel` wrapping ``jit_fn``
        — the recompile budget.  Without a store, build warms THROUGH
        it (one counted trace) and dispatch keeps its guard.
    """

    def __init__(
        self,
        name: str,
        jit_fn: Callable[..., Any],
        *,
        example_args: Sequence[Any] | Callable[[], Sequence[Any]] | None = None,
        config: dict | None = None,
        store=None,
        sentinel=None,
    ):
        if store is not None and config is None:
            raise ValueError(
                f"Program {name!r}: a store needs a config dict to key the "
                "AOT entry (compose it with the family's canonical helper)"
            )
        self.name = name
        self.jit_fn = jit_fn
        self.sentinel = sentinel
        self.config = config
        self.store = store
        self._example_args = example_args
        self._compiled = None
        self.built = False
        self.outcome: str | None = None  # hit/miss/fallback (store mode)
        # Lazy dispatch until built: the sentinel wrapper (budget guard)
        # or the raw jit fn — compile-on-first-call, exactly the
        # pre-Program behavior.
        self.call: Callable[..., Any] = (
            sentinel if sentinel is not None else jit_fn
        )

    # -- introspection ---------------------------------------------------------

    @property
    def compiled(self):
        """The bound ``jax.stages.Compiled`` (None in warm/lazy mode).
        Exposes ``cost_analysis()`` etc. for the bench tools."""
        return self._compiled

    def key(self) -> str:
        """The AOT store key this Program's config digests to (store
        mode only) — what must MATCH between two surfaces for the
        second to start as a pure deserialize."""
        if self.store is None:
            raise ValueError(f"Program {self.name!r} has no store to key for")
        return self.store.key_for(self.config)

    def trace_count(self) -> int:
        """Distinct traces of the underlying jit fn (0 after a pure
        store hit — the zero-traces warm-start contract)."""
        if self.sentinel is not None:
            return self.sentinel.trace_count()
        cache_size = getattr(self.jit_fn, "_cache_size", None)
        return int(cache_size()) if callable(cache_size) else 0

    # -- build ----------------------------------------------------------------

    def _example(self) -> tuple:
        args = self._example_args
        if args is None:
            raise ValueError(
                f"Program {self.name!r} has no example args; pass "
                "example_args= to build it (or dispatch lazily)"
            )
        if callable(args):
            args = args()
        return tuple(args)

    def _build_compiled(self):
        return self.jit_fn.lower(*self._example()).compile()

    def _bind(self, compiled) -> None:
        self._compiled = compiled
        self.call = compiled_fastpath(compiled)
        self.built = True

    def build(self) -> str | None:
        """Obtain the executable; returns the store outcome (hit/miss/
        fallback) or None without a store.  Idempotent.  Safe to fan out
        over a :class:`~.service.CompileService` — concurrent builds of
        DISTINCT Programs compile in parallel (XLA releases the GIL);
        that is :func:`build_programs`."""
        if self.built:
            return self.outcome
        if self.store is not None:
            compiled, outcome = self.store.load_or_compile(
                self.name, self.config, self._build_compiled
            )
            self._bind(compiled)
            self.outcome = outcome
            return outcome
        if self.sentinel is not None:
            # Warm mode: one trace through the guarded wrapper — the
            # budget observes it, dispatch keeps the guard, and the jit
            # cache (not a detached executable) serves the steady state.
            self.sentinel(*self._example())
            self.built = True
            return None
        self._bind(self._build_compiled())
        return None


def build_programs(
    programs: Sequence[Program | None],
    registry=None,
    sink=None,
    max_workers: int | None = None,
) -> None:
    """Fan ``Program.build`` out over a :class:`CompileService`.

    The trainer-side analogue of serving's parallel warmup: N programs
    (train step, eval step, the serve-prewarm predict grid) lower and
    compile CONCURRENTLY in the wall time of the slowest, each timed
    onto ``compile_seconds_total{fn=name}`` inside a ``compile`` span.
    One program builds inline (no pool spin-up for nothing).
    """
    from .service import CompileService

    progs = [p for p in programs if p is not None]
    if not progs:
        return
    if len(progs) == 1:
        progs[0].build()
        return
    with CompileService(
        max_workers=min(len(progs), max_workers or 8),
        registry=registry,
        sink=sink,
    ) as svc:
        for p in progs:
            svc.submit(p.name, p.build)
        svc.wait_all()


# ---------------------------------------------------------------------------
# Canonical config composition — the cross-surface reuse contract.
#
# An ExecutableStore entry is reusable across surfaces iff the config
# digests match; that only happens when every surface composes the dict
# through the SAME function.  One helper per program family lives here.


def default_device_stage(mesh) -> bool:
    """The serving engine's device-staging default (auto: on when every
    mesh device is process-local) — the trainer-side handoff must
    compute the identical value or its entries can never hit."""
    import jax

    return all(
        d.process_index == jax.process_index() for d in mesh.devices.flat
    )


def predict_config(
    mesh,
    dtype: str,
    bucket: int,
    *,
    use_bn: bool,
    conv_impl: str,
    device_stage: bool,
    version: str = "",
    packed: bool = False,
    int8_impl: str = "dot",
    shard_kind: str = "dp",
) -> dict:
    """AOT key config for one serving-forward rung (dtype x bucket).

    Field-for-field the serving engine's historical composition —
    concrete device ids included, because a serialized executable pins
    its compile-time devices (two same-shape meshes on different
    devices must never alias one entry).  ``version`` is the model
    registry's (model, version) identity (serving/registry.py): two
    versions of the same model get DISTINCT store entries, so their
    Program grids coexist in one shared ExecutableStore and a canary or
    rolled-back version warm-starts without evicting the primary's
    rungs.  The unversioned surfaces (single-checkpoint engine, trainer
    handoff) pass the default ``""`` and keep digest-matching each
    other.

    ``packed`` marks the packed ragged-batching forward (segment-id arg,
    ``bucket`` is the rows-capacity) — a packed and a bucketed
    executable at the same shape have different calling conventions and
    must never alias one entry.  ``int8_impl`` names the dense-head
    implementation that ACTUALLY runs (``dot`` | ``pallas``); the engine
    resolves Pallas availability before composing the key, so a
    fallback run never poisons the kernel entry (docs/COMPILE.md).

    ``shard_kind`` names the replica's shard topology
    (parallel/mesh.SHARD_KINDS: ``dp`` | ``tp`` | ``vtp`` | ``ep`` |
    ``pp``).  Together with the ``mesh`` shape field it keys sharded
    predict programs so they NEVER alias a DP entry: a 4-device TP rung
    and four 1-device DP rungs at the same bucket are different
    executables with different collectives.  The default ``"dp"`` keeps
    every pre-existing digest byte-identical in meaning (the field is
    part of the dict either way; all legacy surfaces compose it as
    ``dp``), so trainer-handoff reuse is unchanged.
    """
    import jax

    return {
        "program": "predict_step",
        "dtype": dtype,
        "bucket": int(bucket),
        "shard_kind": str(shard_kind),
        "mesh": {str(k): int(s) for k, s in mesh.shape.items()},
        "devices": [int(d.id) for d in mesh.devices.flat],
        "use_bn": bool(use_bn),
        "conv_impl": conv_impl,
        "device_stage": bool(device_stage),
        "prng_impl": str(jax.config.jax_default_prng_impl),
        "version": str(version),
        "packed": bool(packed),
        "int8_impl": str(int8_impl),
    }


def train_config(mesh, program: str, **extra) -> dict:
    """AOT key config for a trainer-side program (train/eval step, the
    fused run): mesh shape + device ids + PRNG impl, plus whatever
    parameterizes the program (batch sizes, dtype, flags) via
    ``extra``."""
    import jax

    return {
        "program": program,
        "mesh": {str(k): int(s) for k, s in mesh.shape.items()},
        "devices": [int(d.id) for d in mesh.devices.flat],
        "prng_impl": str(jax.config.jax_default_prng_impl),
        **extra,
    }


def predict_store_size(replicas: int, n_dtypes: int, n_buckets: int) -> int:
    """Shared ExecutableStore sizing for a replicas x dtypes x buckets
    predict grid (+ headroom for one config change) — one formula for
    the single engine, the pool, and the trainer handoff, so no surface
    can under-size the store another populates."""
    return 2 * max(1, replicas) * max(1, n_dtypes) * max(1, n_buckets) + 4


def serving_predict_programs(
    mesh,
    variables,
    buckets: Sequence[int],
    *,
    store,
    use_bn: bool = False,
    conv_impl: str = "conv",
    device_stage: bool | None = None,
    version: str = "",
) -> list[Program]:
    """Trainer-side twin of the serving engine's f32 warmup grid — the
    train-to-serve handoff.

    Builds one :class:`Program` per bucket with the engine's EXACT fn
    construction and :func:`predict_config` composition, so the entries
    a training process persists are pure deserializes when the serving
    engine warms the same mesh/buckets from the same store
    (``--serve-prewarm``; pinned in tests/test_program.py).  ``variables``
    is the tree the engine will serve: bare params, or the
    ``{"params", "batch_stats"}`` dict for BN checkpoints — only its
    avals matter here (lowering never reads values).
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models.net import INPUT_SHAPE
    from ..parallel.ddp import make_predict_step
    from ..parallel.mesh import DATA_AXIS

    if device_stage is None:
        device_stage = default_device_stage(mesh)
    fn = make_predict_step(
        mesh, compute_dtype=jax.numpy.float32, use_bn=use_bn,
        conv_impl=conv_impl,
    )
    var_spec = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(
            np.shape(a), np.asarray(a).dtype,
            sharding=getattr(a, "sharding", None),
        ),
        variables,
    )
    input_sharding = NamedSharding(mesh, P(DATA_AXIS))
    programs = []
    for b in buckets:
        x_spec = jax.ShapeDtypeStruct(
            (int(b), *INPUT_SHAPE), np.float32,
            # Staged (device-committed) inputs lower against the data-axis
            # sharding; unstaged lower shardingless — the same fork the
            # engine's _stage makes, and part of the config for the same
            # reason.
            sharding=input_sharding if device_stage else None,
        )
        programs.append(
            Program(
                f"predict_step[f32][{int(b)}]",
                fn,
                example_args=(var_spec, x_spec),
                config=predict_config(
                    mesh, "f32", b, use_bn=use_bn, conv_impl=conv_impl,
                    device_stage=device_stage, version=version,
                ),
                store=store,
            )
        )
    return programs
