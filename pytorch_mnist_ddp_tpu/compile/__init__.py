"""compile: the startup accelerator (docs/COMPILE.md).

Three capabilities, one goal — get from process start to step 0 (or to
an open serving socket) as fast as the hardware allows:

- :mod:`.service` — :class:`CompileService`, a thread pool that runs
  ``lower().compile()`` jobs off the main thread (XLA compilation
  releases the GIL), so independent programs — the fused run, the DDP
  step, every serving bucket — build CONCURRENTLY.  Each job is timed
  onto ``compile_seconds_total{fn=}`` and a ``compile`` span.
- :mod:`.aot` — :class:`ExecutableStore`, serialized AOT executables
  keyed by config + package-source digest + environment; a warm start
  deserializes instead of re-tracing + re-lowering, with a hard
  correctness gate that falls back to a fresh compile on any mismatch.
- :mod:`.overlap` — :class:`StartupTasks`, named concurrent startup
  jobs with a measuring rendezvous (``startup_overlap_ratio``).
- :mod:`.program` — :class:`Program`, the unified compile/AOT/dispatch
  artifact every surface (trainer, serving engine/pool, bench tools)
  constructs its compiled steps through: jit fn + abstract args + AOT
  key + recompile budget + compile spans in one object, with the
  canonical config composition that makes AOT entries reusable ACROSS
  surfaces.

The service and overlap runner are stdlib-only (jobs are opaque
callables); the AOT store and Program touch jax, lazily.
"""

from __future__ import annotations

from .aot import ExecutableStore, source_digest
from .overlap import StartupTasks
from .program import (
    Program,
    build_programs,
    predict_config,
    predict_store_size,
    serving_predict_programs,
    train_config,
)
from .service import CompileJob, CompileService

__all__ = [
    "CompileJob",
    "CompileService",
    "ExecutableStore",
    "Program",
    "StartupTasks",
    "build_programs",
    "predict_config",
    "predict_store_size",
    "serving_predict_programs",
    "source_digest",
    "train_config",
]
