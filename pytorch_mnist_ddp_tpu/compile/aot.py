"""Serialized AOT executables: warm starts skip trace + lower entirely.

The persistent XLA cache (utils/compile_cache.py) removes the *compile*
from a warm start but still pays trace + lower every run — Python work
that for the fused whole-run program is seconds of pure startup.  This
store persists the COMPILED executable itself
(``jax.experimental.serialize_executable``), keyed so a warm start goes
disk → executable with no tracing at all.

Keying — the round-1 postmortem class ("a last-minute RNG flip silently
invalidated the warm cache") is the hazard, so the key must change
whenever the program could:

- a **config key**: every argument that parameterizes the program
  (protocol sizes, flags, arg avals — the caller provides the dict), so
  two configs never alias;
- a **source digest** over every ``.py`` file in this package — any
  commit that touches the model/step/fused code invalidates every entry
  (the same conservatism as hashing the StableHLO, per
  tools/bench_program_hash.py, but computable WITHOUT tracing — which
  is the whole point);
- the environment: jax version, backend platform, device kind, device
  count.

Each entry also stores that metadata in its header, verified again at
load (belt and suspenders): ANY mismatch, unpickling error, or
deserialization failure falls back to a fresh trace + compile and
rewrites the entry — the store is an optimization, never a correctness
surface.  Outcomes land on ``aot_executables_total{outcome=hit|miss|
fallback}`` and as ``aot_executable`` JSONL events.

Concurrency: the store is safe under concurrent readers AND writers on
one directory (the serving replica pool warms N engines against a
single ``--aot-cache``).  Writes go through a per-writer ``mkstemp``
temp file and an atomic ``os.replace`` — no fixed temp name two writers
could interleave into — so a reader only ever sees absent or complete
entries; a same-key write race resolves last-writer-wins with an
equally valid executable, and a double-prune race is absorbed by the
ignore-missing removal.  Pinned by the concurrent-writers test in
tests/test_scaleout.py.

Trust model: entries are pickles (``jax.experimental.
serialize_executable`` is pickle-based end to end), and unpickling
attacker-controlled bytes executes code — the header gate runs AFTER
the unpickle and cannot protect against a hostile file.  Point
``--aot-cache`` only at a directory you own (the store creates missing
directories mode 0700); never at a shared world-writable location on a
multi-user host.  Same trust boundary as jax's own persistent compile
cache directory.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import sys
import tempfile
import time

_FORMAT = 1


def _fault_point(site: str, label: str | None = None) -> None:
    """Dormant chaos hook (serving/faults.py, docs/ROBUSTNESS.md).

    Resolved through ``sys.modules`` so this jax-adjacent module never
    imports the serving package: if nobody imported the faults module,
    nobody installed an injector, and the hook is one dict lookup."""
    faults = sys.modules.get("pytorch_mnist_ddp_tpu.serving.faults")
    if faults is not None:
        faults.fault_point(site, label)

_source_digest_cache: str | None = None


def source_digest() -> str:
    """SHA-256 over every ``.py`` file of this package (sorted relative
    paths + contents).  Cached per process — the tree does not change
    under a running program."""
    global _source_digest_cache
    if _source_digest_cache is not None:
        return _source_digest_cache
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths: list[str] = []
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        paths.extend(
            os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
        )
    digest = hashlib.sha256()
    for path in sorted(paths):
        digest.update(os.path.relpath(path, pkg_root).encode())
        with open(path, "rb") as f:
            digest.update(f.read())
    _source_digest_cache = digest.hexdigest()
    return _source_digest_cache


def _environment() -> dict:
    import jax

    devices = jax.devices()
    return {
        "jax_version": jax.__version__,
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "num_devices": len(devices),
    }


class ExecutableStore:
    """Directory of serialized executables, one file per program key.

    ``load_or_compile(name, config, build_compiled)`` is the whole API:
    ``build_compiled()`` must return a ``jax.stages.Compiled`` (i.e. the
    caller's ``fn.lower(*args).compile()``); the store either
    deserializes a prior run's executable for the same key ("hit") or
    builds fresh and persists ("miss"; "fallback" when an entry existed
    but failed its gate).
    """

    MAX_ENTRIES = 8  # newest kept; key churn (source edits) orphans the rest
    TMP_GRACE_S = 600.0  # crashed-writer .tmp files older than this are reaped

    def __init__(
        self,
        directory: str,
        registry=None,
        sink=None,
        max_entries: int | None = None,
    ):
        self.directory = directory
        self._registry = registry
        self._sink = sink
        if max_entries is not None:
            # Per-store override: a serving engine persists one entry per
            # (dtype, bucket) rung and must hold the WHOLE grid — pruning
            # mid-warmup entries would silently re-miss on warm start.
            if max_entries < 1:
                raise ValueError(f"max_entries must be >= 1, got {max_entries}")
            self.MAX_ENTRIES = max_entries
        # 0700 on creation: entries are pickles (see the module trust
        # model); a directory this process creates must not be writable
        # — or readable — by other users.  Pre-existing directories keep
        # their modes (the operator owns that decision).
        os.makedirs(directory, mode=0o700, exist_ok=True)
        # Entry files honor the process umask like a plain open() would
        # (mkstemp alone gives 0600, which silently breaks a cache dir
        # an operator deliberately shares: the second user's loads all
        # PermissionError into recompile fallbacks).  Probed ONCE here,
        # where construction is single-threaded — the os.umask
        # read-and-restore flip is process-global and would race the
        # concurrent replica warmups writing through this store.
        umask = os.umask(0)
        os.umask(umask)
        self._entry_mode = 0o666 & ~umask

    # -- keying ---------------------------------------------------------------

    def key_for(self, config: dict) -> str:
        """Deterministic key: config + source digest + environment."""
        material = {
            "format": _FORMAT,
            "config": config,
            "source_digest": source_digest(),
            **_environment(),
        }
        blob = json.dumps(material, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.jexec")

    def _record(self, name: str, outcome: str, seconds: float) -> None:
        if self._registry is not None:
            self._registry.counter(
                "aot_executables_total",
                help="serialized-executable store outcomes per load_or_compile",
                outcome=outcome,
            ).inc()
        if self._sink is not None:
            self._sink.emit(
                "aot_executable", fn=name, outcome=outcome, seconds=seconds
            )

    # -- the API --------------------------------------------------------------

    def load_or_compile(self, name: str, config: dict, build_compiled):
        """Return ``(compiled, outcome)``; outcome ∈ hit/miss/fallback.

        A "hit" produced zero traces this process; the returned
        executable is bit-identical in behavior to a fresh compile of
        the same program (pinned by test).  Any problem with the stored
        entry — missing, wrong header, undeserializable — silently
        becomes a fresh compile whose result replaces the entry.
        """
        import time

        t0 = time.perf_counter()
        key = self.key_for(config)
        path = self._path(key)
        outcome = "miss"
        if os.path.exists(path):
            try:
                compiled = self._load(path, key)
                self._record(name, "hit", time.perf_counter() - t0)
                return compiled, "hit"
            except Exception:
                # Stale jax, different machine features, torn write,
                # tampered header: all one answer — recompile.
                outcome = "fallback"
        compiled = build_compiled()
        try:
            self._save(path, key, compiled)
            self._prune()
        except Exception:
            # Not serializable on this backend / unwritable directory:
            # the fresh executable is still perfectly usable.
            pass
        self._record(name, outcome, time.perf_counter() - t0)
        return compiled, outcome

    # -- disk format ----------------------------------------------------------

    def _save(self, path: str, key: str, compiled) -> None:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        entry = {
            "format": _FORMAT,
            "key": key,
            **_environment(),
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        # Concurrent-writer safety (the replica-pool case: N engines
        # warming against ONE --aot-cache dir).  A fixed `path + ".tmp"`
        # name would let two same-key writers interleave into one torn
        # temp file before either renames; mkstemp gives each writer a
        # private file, and os.replace is atomic, so a concurrent reader
        # (or racing writer) only ever sees a complete entry — last
        # writer wins with an equally valid executable.
        fd, tmp = tempfile.mkstemp(
            dir=self.directory, prefix=os.path.basename(path) + ".", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(entry, f)
            # mkstemp creates 0600; restore the umask-governed mode a
            # plain open() would have produced (probed in __init__).
            os.chmod(tmp, self._entry_mode)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def _prune(self) -> None:
        """Keep the newest :attr:`MAX_ENTRIES` entries.  Key churn —
        every source edit changes the digest, every config tweak the
        key — orphans the previous multi-megabyte executable; without
        a bound, an iterating developer's cache grows one serialized
        program per edit, forever."""
        entries = []
        now = time.time()
        for fname in os.listdir(self.directory):
            full = os.path.join(self.directory, fname)
            if fname.endswith(".tmp"):
                # A writer killed between mkstemp and os.replace leaves
                # its uniquely-named temp file behind; nothing else ever
                # deletes it, so reap stale ones here.  The grace period
                # spares a LIVE concurrent writer mid-dump.
                try:
                    if now - os.path.getmtime(full) > self.TMP_GRACE_S:
                        os.remove(full)
                except OSError:
                    pass
                continue
            if not fname.endswith(".jexec"):
                continue
            try:
                entries.append((os.path.getmtime(full), full))
            except OSError:
                continue
        entries.sort(reverse=True)
        for _, full in entries[self.MAX_ENTRIES:]:
            try:
                os.remove(full)
            except OSError:
                pass

    def _load(self, path: str, key: str):
        from jax.experimental.serialize_executable import deserialize_and_load

        # An injected aot_load failure is indistinguishable from a torn
        # or corrupt entry — load_or_compile's fallback path (fresh
        # compile, entry rewritten) is exactly what the chaos schedule
        # exercises.
        _fault_point("aot_load")
        with open(path, "rb") as f:
            entry = pickle.load(f)
        env = _environment()
        expected = {"format": _FORMAT, "key": key, **env}
        for field, want in expected.items():
            if entry.get(field) != want:
                raise ValueError(
                    f"aot entry {os.path.basename(path)} gate mismatch on "
                    f"{field!r}: stored {entry.get(field)!r}, need {want!r}"
                )
        return deserialize_and_load(
            entry["payload"], entry["in_tree"], entry["out_tree"]
        )
