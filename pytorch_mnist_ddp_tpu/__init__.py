"""tpu-mnist-ddp: a TPU-native training framework with the capabilities of
``FlyingAnt2018/pytorch_mnist_ddp``.

The reference (mounted at /root/reference) is a canonical PyTorch MNIST
example trained single-device (mnist.py) or data-parallel with
DistributedDataParallel + NCCL (mnist_ddp.py).  This package provides the
same capability surface built TPU-first on JAX/XLA:

- ``data``      — MNIST IDX pipeline + host-sharded loaders
                  (replaces torchvision.datasets.MNIST / DataLoader /
                  DistributedSampler; SURVEY.md N5-N8)
- ``models``    — the 2-conv CNN as a Flax module with PyTorch-parity init
                  (replaces Net + ATen kernels; SURVEY.md #3, N9)
- ``ops``       — optimizer (Adadelta), LR schedule (StepLR), losses, and
                  Pallas TPU kernels (replaces torch.optim / N11, N12)
- ``parallel``  — device-mesh construction, the jitted data-parallel train
                  step (psum gradient allreduce over ICI/DCN), distributed
                  init from env, and a launch-compatible CLI
                  (replaces torch.distributed / DDP / NCCL; N1-N4)
- ``utils``     — checkpointing, logging formats, RNG threading, timing
                  (replaces torch.save / print surface; N13, N15)
"""

__version__ = "0.1.0"
