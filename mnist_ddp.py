"""Distributed MNIST training CLI — the TPU-native counterpart of the
reference's ``mnist_ddp.py`` (reference mnist_ddp.py:108-203; SURVEY.md §3.1).

Launch surface preserved (SURVEY.md N4):

- ``python -m pytorch_mnist_ddp_tpu.parallel.launch --nproc_per_node=4 \\
  mnist_ddp.py --batch-size 200 --epochs 20`` — the
  ``torch.distributed.launch`` analogue (reference README.md:42); on TPU
  this selects 4 local chips in ONE SPMD process.
- ``RANK``/``WORLD_SIZE`` (+``MASTER_ADDR``/``MASTER_PORT``) or
  ``SLURM_PROCID`` env: multi-host via ``jax.distributed.initialize``.
- Bare ``python mnist_ddp.py ...``: prints "Not using distributed mode"
  and degrades to single-device (reference mnist_ddp.py:25-28).

End of run prints the reference's wall-clock line (its label says "ms",
the value is seconds — preserved, it is the benchmark surface; reference
mnist_ddp.py:200-203).
"""

from __future__ import annotations

import time

from mnist import build_parser, run_cli


def main() -> None:
    p = build_parser()
    # DDP-only flags (reference mnist_ddp.py:132-134).  --local_rank is
    # accepted for launcher compatibility but env vars win, exactly like
    # the reference (declared :132, never read).
    p.add_argument("--local_rank", type=int, default=0,
                   help="accepted for launcher compatibility; env wins")
    p.add_argument("--world-size", type=int, default=1,
                   help="number of processes (env WORLD_SIZE wins)")
    p.add_argument("--dist-url", type=str, default="env://",
                   help="rendezvous URL for multi-host init")
    p.add_argument("--rdzv-timeout-s", type=float, default=None, metavar="S",
                   help="total rendezvous budget: world formation fails "
                        "with a pointed diagnostic instead of hanging past "
                        "it (default: the launcher's RDZV_TIMEOUT_S env, "
                        "else 60)")
    p.add_argument("--rdzv-attempts", type=int, default=None, metavar="K",
                   help="bounded rendezvous attempts within the budget "
                        "(default: RDZV_ATTEMPTS env, else 2)")
    # Beyond-parity parallelism over the mesh's model axis (the reference
    # is DP-only; its README only *mentions* model parallelism, README.md:8).
    p.add_argument("--tp", type=int, default=1, metavar="N",
                   help="tensor-parallel degree: shard the dense head over "
                        "N model-axis devices (data axis = devices / N)")
    p.add_argument("--pp", action="store_true",
                   help="pipeline the two stages (convs | dense head) over "
                        "a 2-wide model axis with microbatched ppermute")
    p.add_argument("--pp-microbatches", type=int, default=2, metavar="M",
                   help="microbatches per shard batch in --pp mode")
    p.add_argument("--syncbn", action="store_true",
                   help="add BatchNorm after each conv with batch statistics "
                        "synced across the data axis (torch.nn.SyncBatchNorm "
                        "semantics; the scaled-batch config of BASELINE.json)")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO-1 data parallelism: shard the Adadelta state "
                        "1/N over the data axis (reduce-scatter gradients, "
                        "shard-local update, all-gather deltas) instead of "
                        "replicating it; numerics match plain DP")
    args = p.parse_args()

    import jax

    if args.no_accel:
        jax.config.update("jax_platforms", "cpu")

    from pytorch_mnist_ddp_tpu.parallel.distributed import init_distributed_mode
    from pytorch_mnist_ddp_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache(
        args.compile_cache_dir, force=args.compile_cache_dir is not None
    )

    # Checkpoint filename quirk preserved: distributed saves mnist_cnn.pt,
    # the non-distributed fallback saves mnist_cnn_.pt (trailing
    # underscore; reference mnist_ddp.py:193-197, SURVEY.md §3.5).
    run_cli(
        args,
        dist_factory=lambda: init_distributed_mode(
            dist_url=args.dist_url,
            rdzv_timeout_s=args.rdzv_timeout_s,
            rdzv_attempts=args.rdzv_attempts,
        ),
        save_path_factory=lambda dist: (
            "mnist_cnn.pt" if dist.distributed else "mnist_cnn_.pt"
        ),
    )


if __name__ == "__main__":
    from pytorch_mnist_ddp_tpu.utils.logging import total_time_line

    start = time.time()
    main()
    print(total_time_line(time.time() - start))
