"""Single-device MNIST training CLI — the TPU-native counterpart of the
reference's ``mnist.py`` (reference mnist.py:73-137; SURVEY.md §3.4).

Same flag surface and printed output; runs on one TPU chip (or CPU with
``--no-accel``/``--no-cuda``).  Training always shuffles — adopting the
``mnist_ddp.py`` semantics over the reference mnist.py quirk where CPU runs
never shuffled (SURVEY.md §3.4).
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native MNIST example")
    p.add_argument("--batch-size", type=int, default=64, metavar="N",
                   help="training batch size (default: 64)")
    p.add_argument("--test-batch-size", type=int, default=1000, metavar="N",
                   help="eval batch size (default: 1000)")
    p.add_argument("--epochs", type=int, default=14, metavar="N",
                   help="number of epochs (default: 14)")
    p.add_argument("--lr", type=float, default=1.0, metavar="LR",
                   help="learning rate (default: 1.0)")
    p.add_argument("--gamma", type=float, default=0.7, metavar="M",
                   help="lr decay factor per epoch (default: 0.7)")
    p.add_argument("--no-cuda", "--no-accel", dest="no_accel",
                   action="store_true", default=False,
                   help="force CPU (accepts the reference's --no-cuda)")
    p.add_argument("--dry-run", action="store_true", default=False,
                   help="run a single batch per epoch")
    p.add_argument("--seed", type=int, default=1, metavar="S",
                   help="random seed (default: 1)")
    p.add_argument("--log-interval", type=int, default=10, metavar="N",
                   help="batches between train log lines (default: 10)")
    p.add_argument("--save-model", action="store_true", default=False,
                   help="save the final model checkpoint")
    p.add_argument("--resume", type=str, default=None, metavar="PATH",
                   help="load model parameters (and BN running statistics, "
                        "if present) from a saved checkpoint (.pt or .npz) "
                        "and continue training; the optimizer starts fresh "
                        "(the checkpoint format stores only the model, "
                        "like the reference's)")
    p.add_argument("--save-state", type=str, default=None, metavar="PATH",
                   help="save the FULL training state (params, Adadelta "
                        "accumulators, step/epoch counters, BN stats) at "
                        "the end of the run; --resume-state continues from "
                        "it bit-identically")
    p.add_argument("--resume-state", type=str, default=None, metavar="PATH",
                   help="restore a --save-state archive and train --epochs "
                        "MORE epochs, continuing the LR schedule, shuffle "
                        "stream, and epoch numbering exactly where the "
                        "saved run stopped")
    p.add_argument("--fused", action="store_true", default=False,
                   help="run the whole multi-epoch training as one device "
                        "call over an HBM-resident dataset (fastest; same "
                        "printed output, emitted after the run completes)")
    p.add_argument("--pregather", action="store_true", default=False,
                   help="(--fused only) pre-permuted-epoch input path: one "
                        "big gather per epoch + contiguous per-step slices "
                        "(parallel/fused.py pregather; bit-identical "
                        "batches, different input HLO)")
    p.add_argument("--conv-impl", type=str, default="conv",
                   choices=["conv", "im2col_c1", "im2col"],
                   help="convolution lowering (models/net.py): XLA's native "
                        "conv (default), or GEMM-lowered via im2col for "
                        "conv1 only / both convs — conv1's C_in=1 windows "
                        "cannot tile the MXU (docs/PERF.md); same params, "
                        "same math, different reduction tree")
    p.add_argument("--pallas-opt", action="store_true", default=False,
                   help="use the fused Pallas Adadelta kernel for the "
                        "optimizer update (ops/pallas_adadelta.py)")
    p.add_argument("--bf16", action="store_true", default=False,
                   help="bfloat16 activations/matmuls (MXU-native width; "
                        "params, optimizer state, and log_softmax/NLL stay "
                        "fp32)")
    p.add_argument("--data-root", type=str, default="./data",
                   help="MNIST IDX directory")
    p.add_argument("--profile", type=str, default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into DIR "
                        "(view with TensorBoard/XProf)")
    p.add_argument("--step-stats", action="store_true", default=False,
                   help="print per-epoch host-side step latency summaries "
                        "(per-batch path only)")
    p.add_argument("--telemetry-dir", type=str, default=None, metavar="DIR",
                   help="write structured telemetry into DIR: JSONL "
                        "step/epoch/eval events (chief-only in distributed "
                        "mode) plus a Prometheus text exposition "
                        "(metrics.prom) at end of run; stdout is unchanged "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--aot-cache", type=str, default=None, metavar="DIR",
                   help="persist the run's compiled Programs (the fused "
                        "whole-run, or the per-batch train/eval steps) as "
                        "serialized AOT executables in DIR: a warm start "
                        "deserializes instead of re-tracing + re-lowering, "
                        "falling back to a fresh compile on any config/"
                        "source/jax mismatch (docs/COMPILE.md)")
    p.add_argument("--serve-prewarm", action="store_true", default=False,
                   help="(per-batch, with --aot-cache) also build the "
                        "serving engine's f32 predict grid into the AOT "
                        "cache through the canonical Program config — a "
                        "serving engine warming the matching mesh/buckets "
                        "from the same --aot-cache then starts with zero "
                        "compiles (the train-to-serve handoff, "
                        "docs/COMPILE.md)")
    p.add_argument("--compile-cache-dir", type=str, default=None,
                   metavar="DIR",
                   help="persistent XLA compile-cache directory (default: "
                        "JAX_COMPILATION_CACHE_DIR, else the utils/"
                        "cache_dir root); naming one explicitly also "
                        "enables the cache on the CPU backend, which is "
                        "otherwise skipped (single-host CI use)")
    p.add_argument("--prefetch-depth", type=int, default=2, metavar="N",
                   help="device-resident input batches kept in flight "
                        "ahead of the step loop (per-batch path; "
                        "data/prefetch.py): 2 double-buffers the next "
                        "shard's H2D under the current step, 0 restores "
                        "the synchronous serial feed — batches (and all "
                        "printed output) are bit-identical either way. "
                        "The --fused path keeps the whole dataset "
                        "HBM-resident, so the flag is a no-op there "
                        "(docs/DATA.md)")
    p.add_argument("--train-limit", type=int, default=0, metavar="N",
                   help="smoke-only: truncate train/test sets to N samples "
                        "(exercises the full program shape in seconds; "
                        "never a headline number — bench.py refuses to "
                        "snapshot truncated runs)")
    # Resilient training runtime (pytorch_mnist_ddp_tpu/resilience/,
    # docs/ROBUSTNESS.md trainer section).  All default to off: the
    # flagless run builds none of it and stdout stays byte-identical.
    p.add_argument("--checkpoint-every-steps", type=int, default=0,
                   metavar="N",
                   help="write a mid-epoch full-state archive to the "
                        "--save-state path every N optimizer steps, with a "
                        "rotating last/last-1 publish so a kill at ANY "
                        "point (including mid-save) leaves a loadable "
                        "archive; --resume-state continues bit-identically "
                        "from the exact batch cursor.  SIGTERM/SIGINT also "
                        "land an emergency archive at the next step "
                        "boundary and exit 128+signum (per-batch DP paths; "
                        "requires --save-state)")
    p.add_argument("--preempt-grace-s", type=float, default=30.0,
                   metavar="S",
                   help="bounded grace for the emergency save after "
                        "SIGTERM/SIGINT: if the clean save+exit has not "
                        "finished in S seconds the process force-exits "
                        "with the same code (default: 30)")
    p.add_argument("--loss-guard", action="store_true", default=False,
                   help="guard each step's loss (NaN/Inf or a spike over "
                        "the accepted-loss EWMA): the poisoned update is "
                        "rolled back from a pre-step snapshot and retried "
                        "— first at the original LR (a transient anomaly "
                        "heals with zero numeric divergence), then with "
                        "LR backoff — aborting with one diagnostic when "
                        "--anomaly-budget is exhausted.  Syncs the loss to "
                        "host every step (the --step-stats trade)")
    p.add_argument("--spike-factor", type=float, default=10.0, metavar="F",
                   help="--loss-guard spike threshold: loss > F x EWMA of "
                        "accepted losses is an anomaly; 0 disables spike "
                        "detection (NaN/Inf only; default: 10)")
    p.add_argument("--anomaly-budget", type=int, default=3, metavar="K",
                   help="rollback-and-retry attempts per step before the "
                        "run aborts (default: 3)")
    p.add_argument("--anomaly-lr-backoff", type=float, default=0.5,
                   metavar="F",
                   help="LR multiplier applied from the second retry of an "
                        "anomalous step on (the first retry keeps the "
                        "original LR so a transient heals bit-exactly; "
                        "default: 0.5)")
    p.add_argument("--step-timeout-s", type=float, default=0.0, metavar="S",
                   help="hung-step watchdog: emit a train_stall event (and "
                        "train_stalls_total) when a step exceeds S seconds "
                        "(includes the first step's compile — budget for "
                        "it); 0 disables.  Enabling syncs each step's "
                        "output to host (the watchdog needs a completion "
                        "signal to watch)")
    p.add_argument("--stall-abort", action="store_true", default=False,
                   help="with --step-timeout-s: exit 75 (EX_TEMPFAIL) on a "
                        "stalled step after flushing telemetry, instead of "
                        "only reporting it")
    p.add_argument("--chaos", type=str, default=None, metavar="SPEC",
                   help="deterministic fault injection for the trainer "
                        "(serving/faults.py grammar; sites step/data_next/"
                        "ckpt_save, ops fail/hang/kill/nan — e.g. "
                        "'kill:step:after=7' or 'nan:step:after=5'): the "
                        "chaos harness tools/train_chaos.py drives kill/"
                        "resume/verify schedules through this flag")
    p.add_argument("--chaos-seed", type=int, default=0, metavar="S",
                   help="seed for probabilistic (p=) chaos triggers")
    # Elastic distributed runtime (parallel/elastic.py + launch.py,
    # docs/ROBUSTNESS.md elastic section).
    p.add_argument("--elastic", action="store_true", default=False,
                   help="elastic-restart contract: when the --save-state "
                        "archive already exists, resume from it and read "
                        "--epochs as the TOTAL target (the supervising "
                        "launcher's gang restarts get this automatically "
                        "via ELASTIC_RESTART_COUNT)")
    p.add_argument("--resume-reshard", action="store_true", default=False,
                   help="accept a mid-epoch archive saved at a DIFFERENT "
                        "world size: same seed + global batch consume the "
                        "exact same global batches over the new rank "
                        "count (sampler contract) — a sample-exact "
                        "continuation with FP-level drift (reductions "
                        "re-associate), not bit-equality; without this "
                        "flag the world-fingerprint mismatch is refused")
    return p


def run_cli(args, dist_factory, save_path_factory) -> None:
    """Shared CLI tail for mnist.py / mnist_ddp.py: install the chaos
    schedule (if any), run fit(), and turn an exhausted anomaly budget
    into ONE clear stderr diagnostic + a conventional non-zero exit
    (EXIT_ANOMALY) instead of a traceback — the operator's signal that
    the run ABORTED on a training anomaly, not crashed by accident."""
    import sys

    from pytorch_mnist_ddp_tpu.resilience import (
        EXIT_ANOMALY,
        AnomalyBudgetExhausted,
    )
    from pytorch_mnist_ddp_tpu.trainer import fit

    if getattr(args, "chaos", None):
        from pytorch_mnist_ddp_tpu.serving.faults import FaultInjector, install

        install(
            FaultInjector(args.chaos, seed=getattr(args, "chaos_seed", 0))
        ).start()

    dist = dist_factory()
    try:
        fit(args, dist, save_path=save_path_factory(dist))
    except AnomalyBudgetExhausted as e:
        print(f"fatal: {e}", file=sys.stderr)
        raise SystemExit(EXIT_ANOMALY)


def main() -> None:
    args = build_parser().parse_args()

    import jax

    if args.no_accel:
        jax.config.update("jax_platforms", "cpu")

    from pytorch_mnist_ddp_tpu.parallel.distributed import DistState
    from pytorch_mnist_ddp_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache(
        args.compile_cache_dir, force=args.compile_cache_dir is not None
    )

    # Single-device semantics, like the reference mnist.py (one device, no
    # collectives); the reference saves to mnist_cnn.pt (mnist.py:133).
    run_cli(
        args,
        dist_factory=lambda: DistState(devices=jax.devices()[:1]),
        save_path_factory=lambda dist: "mnist_cnn.pt",
    )


if __name__ == "__main__":
    main()
