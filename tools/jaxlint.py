#!/usr/bin/env python
"""Repo entry point for the jaxlint analyzer (thin shim).

Equivalent to ``python -m pytorch_mnist_ddp_tpu.analysis``; exists so the
analyzer is runnable from a checkout without installing the package
(``python tools/jaxlint.py pytorch_mnist_ddp_tpu/ --fail-on-warning``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_mnist_ddp_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
