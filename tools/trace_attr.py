"""Distill an XProf/JAX profiler trace into a per-op attribution JSON.

Round-3 verdict item 1: the headline benchmark's warm steady state sits
~10x above compute-bound (MFU ~9%) and the captured trace was lost to a
machine reset before anyone read it.  This tool turns a
``jax.profiler.trace`` output directory into a SMALL committed artifact:
total device-busy time, the idle-gap share, and a per-category / per-op
breakdown — enough to decide where the ~0.8 ms/step goes without keeping
the multi-MB trace alive.

Works on the Chrome-trace JSON (``*.trace.json.gz``) that every backend
emits (the .xplane.pb needs tensorboard's profile plugin, not installed
here).  Device selection is heuristic but resilient:

* prefer events whose args carry ``hlo_op``/``hlo_module`` (the XLA
  executor lines; on CPU that is the PjRt client thread, on TPU the
  TensorCore "XLA Ops" lines),
* attribute time per THREAD and report the busiest op timeline, so
  overlapping host threads can't double-count device time,
* categorize ops by HLO-name heuristics (convolution / dot / rng / copy /
  collective / gather-scatter / reduce / other-fusion / infeed).

Usage:
    python tools/trace_attr.py TRACE_DIR [--out attr.json] [--top N]

Prints the JSON to stdout (and writes --out if given).  Exit 1 with an
error JSON if no trace file or no op events are found.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import sys
from collections import defaultdict

# Category heuristics over HLO op / fusion names, first match wins.  A
# fusion is named after its root, so "loop_convolution_fusion" lands in
# convolution — the MXU/VPU split stays honest.
_CATEGORIES = (
    ("convolution", re.compile(r"conv")),
    ("matmul", re.compile(r"\bdot|dot_general|matmul|gemm|einsum")),
    ("rng", re.compile(r"rng|threefry|philox|erf_inv|random")),
    ("collective", re.compile(
        r"all-reduce|all_reduce|all-gather|all_gather|reduce-scatter"
        r"|reduce_scatter|collective|permute|all-to-all|all_to_all")),
    ("gather_scatter", re.compile(r"gather|scatter|dynamic-slice|dynamic_slice"
                                  r"|dynamic-update|dynamic_update")),
    ("copy_layout", re.compile(r"copy|transpose|bitcast|reshape|broadcast"
                               r"|convert|slice|concatenate|pad")),
    ("reduce", re.compile(r"reduce|argmax|argmin|sort|top-k|topk")),
    ("infeed_outfeed", re.compile(r"infeed|outfeed|send|recv|transfer")),
    ("elementwise_fusion", re.compile(r"fusion|add|multiply|subtract|divide"
                                      r"|maximum|minimum|exp|log|tanh|select"
                                      r"|compare|map")),
)


def _categorize(name: str) -> str:
    low = name.lower()
    for cat, pat in _CATEGORIES:
        if pat.search(low):
            return cat
    return "other"


def _load_trace(trace_dir: str) -> dict:
    if os.path.isfile(trace_dir):
        candidates = [trace_dir]
    else:
        candidates = sorted(
            glob.glob(os.path.join(
                trace_dir, "plugins", "profile", "*", "*.trace.json.gz"))
            + glob.glob(os.path.join(trace_dir, "*.trace.json.gz"))
        )
    if not candidates:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_dir}")
    path = candidates[-1]  # latest capture wins
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return json.load(f)


def attribute(trace_dir: str, top: int = 25) -> dict:
    data = _load_trace(trace_dir)
    events = data.get("traceEvents", [])
    proc_names: dict[int, str] = {}
    thread_names: dict[tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"]["name"]
        elif e.get("name") == "thread_name":
            thread_names[(e["pid"], e.get("tid"))] = e["args"]["name"]

    # Pass 1: collect op events — complete events whose args identify an
    # HLO op, or that live on an "XLA Ops"-style line (TPU traces name the
    # TensorCore op lines, not the args).
    raw: dict[tuple[int, int], list] = defaultdict(list)
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        key = (e.get("pid"), e.get("tid"))
        tname = thread_names.get(key, "")
        is_op = "hlo_op" in args or "hlo_module" in args or \
            re.search(r"XLA Ops|TensorCore|Steps", tname)
        if not is_op:
            continue
        op = args.get("hlo_op", e.get("name", "?"))
        raw[key].append(
            (float(e.get("ts", 0.0)), float(e.get("dur", 0.0)), op))
    if not raw:
        raise ValueError("no HLO op events found in trace")

    # Pass 2: per-thread SELF-time attribution.  Chrome X events on one
    # thread can nest (a `while` wrapping its body ops); naive summing
    # double-counts the wrapper.  A stack walk charges each op only the
    # time not covered by its children — on a flat device line this
    # degrades to self == dur.
    per_thread: dict[tuple[int, int], dict] = {}
    for key, evs in raw.items():
        evs.sort(key=lambda t: (t[0], -t[1]))
        rec = {"busy": 0.0, "n": len(evs), "t0": evs[0][0], "t1": 0.0,
               "overlap": 0, "ops": defaultdict(lambda: [0.0, 0])}
        stack: list[list] = []  # [end_ts, op, child_time_us, start_ts]
        def _pop(entry):
            end, op, child, start = entry
            self_us = max(end - start - child, 0.0)
            rec["busy"] += self_us
            slot = rec["ops"][op]
            slot[0] += self_us
            slot[1] += 1
            if stack:
                # Charge this event's span to its ancestors' child-time.
                # Nested events charge the immediate parent in full; an
                # overlapping NON-nested event (end outruns the parent's)
                # is split — the in-parent slice to the parent, the
                # overflow to whichever ancestor spans it — so neither the
                # parent's self-time is zeroed (old undercount) nor the
                # overflow double-counted at the grandparent (overcount).
                seg_start, overflowed = start, False
                for frame in reversed(stack):
                    contrib = min(end, frame[0]) - seg_start
                    if contrib > 0:
                        frame[2] += contrib
                    if end <= frame[0]:
                        break
                    overflowed = True
                    seg_start = max(seg_start, frame[0])
                if overflowed:
                    rec["overlap"] += 1
        for ts, dur, op in evs:
            while stack and stack[-1][0] <= ts:
                _pop(stack.pop())
            stack.append([ts + dur, op, 0.0, ts])
            rec["t1"] = max(rec["t1"], ts + dur)
        while stack:
            _pop(stack.pop())
        per_thread[key] = rec

    # The busiest op line IS the device timeline (XLA executes one op at a
    # time per core); other qualifying lines are reported but not summed.
    # TPU traces also carry a "Steps" line whose events span whole steps —
    # it would trivially win on busy-time and reduce the table to step
    # numbers, so it is only eligible when nothing better qualified.
    def _rank(k):
        tname = thread_names.get(k, "")
        is_steps = bool(re.search(r"\bSteps\b", tname)) and not re.search(
            r"XLA Ops|TensorCore", tname)
        return (0 if is_steps else 1, per_thread[k]["busy"])

    main_key = max(per_thread, key=_rank)
    main = per_thread[main_key]
    span_us = main["t1"] - main["t0"]
    busy_us = main["busy"]

    by_cat: dict[str, list[float]] = defaultdict(lambda: [0.0, 0])
    for op, (dur, n) in main["ops"].items():
        c = by_cat[_categorize(op)]
        c[0] += dur
        c[1] += n
    top_ops = sorted(main["ops"].items(), key=lambda kv: -kv[1][0])[:top]

    return {
        "metric": "trace_attribution",
        "process": proc_names.get(main_key[0], "?"),
        "thread": thread_names.get(main_key, "?"),
        "op_events": main["n"],
        # Non-nested overlapping events seen on the main line; their spans
        # were redistributed across ancestors during the self-time walk,
        # so busy_s stays exact — nonzero just flags that the trace was
        # not purely nested (per-op attribution is then approximate).
        "overlap_events": int(main["overlap"]),
        "span_s": round(span_us / 1e6, 6),
        "busy_s": round(busy_us / 1e6, 6),
        "gap_share": round(1.0 - busy_us / span_us, 3) if span_us else None,
        "by_category": {
            cat: {"time_s": round(d / 1e6, 9), "count": n,
                  "share_of_busy": round(d / busy_us, 3) if busy_us else None}
            for cat, (d, n) in sorted(by_cat.items(), key=lambda kv: -kv[1][0])
        },
        "top_ops": [
            {"op": op, "time_s": round(d / 1e6, 9), "count": n,
             "share_of_busy": round(d / busy_us, 3) if busy_us else None}
            for op, (d, n) in top_ops
        ],
        "other_op_lines": {
            f"{proc_names.get(k[0], '?')}:{thread_names.get(k, '?')}":
                round(v["busy"] / 1e6, 6)
            for k, v in per_thread.items() if k != main_key
        },
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("trace_dir")
    p.add_argument("--out", default=None)
    p.add_argument("--top", type=int, default=25)
    args = p.parse_args()
    try:
        result = attribute(args.trace_dir, args.top)
    except (OSError, ValueError, KeyError) as e:
        result = {"metric": "trace_attribution", "error": repr(e)}
        print(json.dumps(result))
        return 1
    out = json.dumps(result, indent=1)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
