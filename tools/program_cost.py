"""XLA's own cost model for the fused benchmark program (no TPU needed).

docs/PERF.md bounds the ~0.8 ms/step floor with hand-counted FLOPs and
an activation-traffic estimate; this tool replaces the hand estimate
with XLA's `Compiled.cost_analysis()` on the EXACT whole-run program
the headline benchmark compiles (same builder, same protocol shapes,
1-device mesh — the tools/bench_program_hash.py construction).  Derived
per-step numbers divide by the protocol's 6000 train steps.

Flop counts are backend-neutral; `bytes accessed` reflects the
compiling backend's (CPU) fusion/layout decisions, so treat it as an
order-of-magnitude HBM-traffic proxy, not a TPU measurement — both are
printed with that caveat in the JSON.

Usage: python tools/program_cost.py [--epochs N] (prints ONE JSON line)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=200)
    p.add_argument("--conv-impl", type=str, default="conv",
                   choices=["conv", "im2col_c1", "im2col"],
                   help="cost-analyze a GEMM-lowered conv variant "
                        "(models/net.py CONV_IMPLS): offline evidence that "
                        "the alternative lowering does not change the FLOP "
                        "count, only the op mix/layout")
    args = p.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "rbg")  # the bench's RNG

    import jax.numpy as jnp
    import numpy as np

    from pytorch_mnist_ddp_tpu.parallel.fused import (
        device_put_dataset,
        make_fused_run,
    )
    from pytorch_mnist_ddp_tpu.parallel.mesh import make_mesh
    from pytorch_mnist_ddp_tpu.utils.flops import run_flops

    train_size, test_size = 60000, 10000
    mesh = make_mesh(num_data=1, devices=jax.devices()[:1])
    rng = np.random.RandomState(0)
    tr = device_put_dataset(
        rng.randint(0, 256, (train_size, 28, 28), dtype=np.uint8),
        rng.randint(0, 10, train_size), mesh,
    )
    te = device_put_dataset(
        rng.randint(0, 256, (test_size, 28, 28), dtype=np.uint8),
        rng.randint(0, 10, test_size), mesh,
    )
    run_fn, num_batches = make_fused_run(
        mesh, train_size, test_size, args.batch_size, 1000, args.epochs,
        from_key=True, conv_impl=args.conv_impl,
    )
    lrs = jnp.asarray([1.0 * 0.7 ** e for e in range(args.epochs)],
                      jnp.float32)
    # The exact headline program as a Program artifact (compile/
    # program.py) — the same build path trainer.py dispatches through,
    # so the cost analysis can never drift from the shipped executable.
    from pytorch_mnist_ddp_tpu.compile import Program

    program = Program(
        "fused_run",
        run_fn,
        example_args=(
            jax.random.PRNGKey(0), *tr, *te,
            jax.random.PRNGKey(2), jax.random.PRNGKey(3), lrs,
        ),
    )
    program.build()
    cost = program.compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns one dict per device
        cost = cost[0]
    flops = float(cost.get("flops", 0.0))
    byt = float(cost.get("bytes accessed", 0.0))
    # XLA's cost analysis counts each `while`/scan BODY ONCE (trip counts
    # are not multiplied in), so `flops` here is approximately ONE train
    # step + ONE eval batch + init — which is exactly the per-iteration
    # number docs/PERF.md bounds.  The reconciliation below makes the
    # agreement (or any drift) explicit.
    from pytorch_mnist_ddp_tpu.utils.flops import (
        forward_flops_per_sample,
        train_step_flops_per_sample,
    )

    step_gf = train_step_flops_per_sample() * args.batch_size / 1e9
    eval_gf = forward_flops_per_sample() * 1000 / 1e9
    out = {
        "metric": "fused_program_cost",
        "backend_compiled_for": jax.default_backend(),
        "conv_impl": args.conv_impl,
        "epochs": args.epochs,
        "train_steps": args.epochs * num_batches,
        "xla_bodies_once_gflops": round(flops / 1e9, 2),
        "analytic_step_plus_eval_batch_gflops": round(step_gf + eval_gf, 2),
        "analytic_step_gflops": round(step_gf, 2),
        "analytic_eval_batch_gflops": round(eval_gf, 2),
        "analytic_run_total_gflops": round(
            run_flops(train_size, test_size, args.epochs) / 1e9, 1
        ),
        # CPU-layout proxy, bodies-once, order-of-magnitude only.
        "xla_bytes_accessed_bodies_once_gb": round(byt / 1e9, 2),
        "notes": "XLA cost analysis counts scan bodies once (trip counts "
                 "not multiplied): flops ~= one train step + one eval "
                 "batch + init.  Flops backend-neutral; bytes reflect "
                 "the CPU compilation's fusion/layout, not the TPU's",
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
