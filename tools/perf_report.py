"""Turn the tunnel-window attribution artifacts into the docs/PERF.md verdict.

The watcher runs this after its ladder/trace legs each tunnel window:
it reads whichever of ``bench_r*_stepattr.json`` (plus the bf16 and
conv-impl ladder variants) / ``bench_r*_attr.json`` /
``bench_r*_warm.json`` exist (newest round first, so a round-5 artifact
shadows its round-4 namesake), computes the rung deltas and the run_s
reconciliation from docs/PERF.md's decision rules, APPENDS a dated
analysis block to docs/PERF.md, and prints the same block to stdout —
so the analysis lands as a commit even when the window opens after the
interactive session died (the round-3 failure mode for evidence).

Usage: python tools/perf_report.py [--no-write]
Exit 0 with a block if at least the ladder artifact exists; 1 otherwise.

PR 3: ``--telemetry DIR`` instead summarizes a ``--telemetry-dir``
telemetry directory (the obs package's JSONL events, docs/OBSERVABILITY
.md): step count/latency percentiles (the repo-shared linear
interpolation), per-epoch throughput, eval accuracy, run wall time.
Stdout-only — telemetry summaries are operator reads, not PERF.md
verdicts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERF_MD = os.path.join(REPO, "docs", "PERF.md")

# Headline protocol facts (bench.py PROTOCOL): 20 epochs x 300 steps,
# 10 eval batches per epoch.
TRAIN_STEPS = 6000
EVAL_BATCHES = 200
EPOCHS = 20


def _detect_prefix():
    """The newest round whose BASELINE ladder exists (bench_rN_stepattr
    .json, glob-resolved so a future round needs no edit here).  Every
    companion artifact is then loaded under the SAME prefix — mixing
    rounds would compute flip/keep verdicts from numbers measured under
    different cache/throughput regimes (tunnel throughput is bimodal)."""
    import glob
    import re

    rounds = []
    for path in glob.glob(os.path.join(REPO, "bench_r*_stepattr.json")):
        m = re.match(r"bench_r(\d+)_stepattr\.json$", os.path.basename(path))
        if m:
            rounds.append(int(m.group(1)))
    return f"bench_r{max(rounds)}_" if rounds else None


def _load(suffix, prefix):
    try:
        with open(os.path.join(REPO, prefix + suffix)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_us(v):
    return "—" if v is None else f"{v:,.0f} µs"


def build_report() -> str | None:
    prefix = _detect_prefix()
    if prefix is None:
        return None
    ladder = _load("stepattr.json", prefix)
    if not ladder or ladder.get("full") is None:
        return None
    bf16 = _load("stepattr_bf16.json", prefix)
    attr = _load("attr.json", prefix)
    warm = _load("warm.json", prefix)
    # Conv-lowering ladder variants (round-5: the conv1 MXU question).
    conv_c1 = _load("stepattr_im2col_c1.json", prefix)
    conv_all = _load("stepattr_im2col.json", prefix)
    # Batch-scaling diagnostic ladder (batch 1000 vs the baseline 200).
    # Both sides of the verdict's ratio are cross-window minima: the
    # watcher records each window's run to `_b1000_run.json` and
    # promotes onto this artifact through the window_promote `rungs`
    # rule (full-rung tie-break), the same discipline as the unsuffixed
    # baseline — docs/PERF.md rule 2 (decision ratios must not mix
    # bimodal throughput modes; round-5 advisor finding).
    b1000 = _load("stepattr_b1000.json", prefix)

    g = ladder.get  # µs per iteration, or None
    lines = []
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    lines.append(f"### Window analysis — {stamp} "
                 f"({ladder.get('device_kind', '?')}; artifacts {prefix}*)")
    lines.append("")
    lines.append("| Rung | µs/iter |")
    lines.append("|---|---|")
    for k in ("empty_scan", "gather_norm", "gather_epoch", "fwd",
              "fwd_bwd", "full_nodrop", "full", "full_nogather",
              "full_pregather", "eval"):
        if g(k) is not None:
            lines.append(f"| {k} | {g(k):,.1f} |")
    lines.append("")

    def delta(a, b):
        return None if g(a) is None or g(b) is None else g(a) - g(b)

    attrib = [
        ("scan-loop overhead", g("empty_scan")),
        ("input (per-step gather+normalize)", delta("gather_norm",
                                                    "empty_scan")),
        ("input (pregather alternative)", delta("gather_epoch",
                                                "empty_scan")),
        ("forward compute", delta("fwd", "empty_scan")),
        ("backward extra", delta("fwd_bwd", "fwd")),
        ("optimizer + input (full_nodrop − fwd_bwd)",
         delta("full_nodrop", "fwd_bwd")),
        ("dropout/RNG (full − full_nodrop)", delta("full", "full_nodrop")),
        ("gather cross-check (full − full_nogather)",
         delta("full", "full_nogather")),
        ("pregather end-to-end win (full − full_pregather)",
         delta("full", "full_pregather")),
    ]
    lines.append("| Attribution | µs/step |")
    lines.append("|---|---|")
    for name, v in attrib:
        lines.append(f"| {name} | {_fmt_us(v)} |")
    lines.append("")

    # run_s reconciliation against the warm headline row, if present.
    if g("full") is not None and g("eval") is not None:
        pred = (TRAIN_STEPS * g("full") + EVAL_BATCHES * g("eval")) / 1e6
        lines.append(f"Reconstructed run_s from the ladder: "
                     f"{TRAIN_STEPS}×full + {EVAL_BATCHES}×eval = "
                     f"**{pred:.2f} s**.")
        if warm and warm.get("run_s"):
            got = warm["run_s"]
            lines.append(f"Measured warm `run_s` ({warm.get('cache')} row): "
                         f"**{got:.2f} s** — "
                         f"{'reconciles' if abs(pred - got) / got < 0.25 else 'DOES NOT reconcile'} "
                         f"({pred / got:,.2f}×); residual outside the step "
                         f"program: {got - pred:+.2f} s.")
        lines.append("")

    # Decision rules (docs/PERF.md).
    verdicts = []
    win = delta("full", "full_pregather")
    if win is not None and g("full"):
        share = win / g("full")
        if share > 0.05:
            verdicts.append(
                f"**Flip to pregather**: the pregather step is "
                f"{share:.0%} faster ({win:,.1f} µs/step); confirm with "
                f"`bench.py --pregather` then make it the default and "
                f"re-warm in-window."
            )
        else:
            verdicts.append(
                f"Input path verdict: pregather wins only {share:.0%} "
                f"per step — keep the shipped per-step gather."
            )
    fb, fu = g("fwd_bwd"), g("full")
    if fb is not None and fu:
        if fb / fu > 0.8:
            verdicts.append(
                f"The step is {fb / fu:.0%} fwd+bwd compute: the floor is "
                f"compute/layout-bound at these conv shapes, not overhead "
                f"— see the per-op table ({'bench_r*_attr.json' if attr else 'trace pending'}) "
                f"for the conv1/conv2 split."
            )
        else:
            verdicts.append(
                f"fwd+bwd is only {fb / fu:.0%} of the full step — "
                f"{fu - fb:,.1f} µs/step rides input/optimizer/dropout; "
                f"see the attribution rows above."
            )
    if bf16 and bf16.get("full") and fu:
        verdicts.append(
            f"bf16 ladder: full {bf16['full']:,.1f} µs vs f32 {fu:,.1f} µs "
            f"({1 - bf16['full'] / fu:+.0%})."
        )
    for label, lad in (("im2col_c1", conv_c1), ("im2col", conv_all)):
        if lad and lad.get("full") and fu:
            win = 1 - lad["full"] / fu
            verdicts.append(
                f"conv ladder ({label}): full {lad['full']:,.1f} µs vs "
                f"native-conv {fu:,.1f} µs ({win:+.0%})"
                + (f"; fwd {lad['fwd']:,.1f} vs {g('fwd'):,.1f} µs"
                   if lad.get("fwd") and g("fwd") else "")
                + (" — flip `--conv-impl` after an end-to-end "
                   "`bench.py --conv-impl` row confirms" if win > 0.05
                   else " — keep the native conv.")
            )
    if b1000 and b1000.get("full") and fu and b1000.get("batch"):
        base_batch = ladder.get("batch") or 200
        ratio = b1000["full"] / fu
        scale = b1000["batch"] / base_batch
        if ratio < 0.4 * scale:
            verdicts.append(
                f"Batch-scaling: full at batch {b1000['batch']} is only "
                f"{ratio:.1f}x the batch-{base_batch} step "
                f"({scale:.0f}x the work) — the step is dominated by "
                f"per-op/latency overhead inside the scan body; fewer, "
                f"larger ops (or bigger per-step batches) are the lever "
                f"(ratio of min-promoted artifacts, both sides)."
            )
        else:
            verdicts.append(
                f"Batch-scaling: full scales {ratio:.1f}x for {scale:.0f}x "
                f"batch — the step is bandwidth/compute-bound at these "
                f"shapes, not overhead-bound (ratio of min-promoted "
                f"artifacts, both sides)."
            )
    if attr and attr.get("gap_share") is not None:
        verdicts.append(
            f"Trace: device busy {attr.get('busy_s')}s over "
            f"{attr.get('span_s')}s span — gap share "
            f"{attr['gap_share']:.0%}; top category: "
            f"{next(iter(attr.get('by_category') or {}), '?')}."
        )
    for v in verdicts:
        lines.append(f"- {v}")
    lines.append("")
    return "\n".join(lines)


def summarize_telemetry(directory: str) -> str | None:
    """Digest every ``*.jsonl`` event file in ``directory`` (obs/events
    schema) into an operator summary, or None when nothing parses."""
    import glob

    sys.path.insert(0, REPO)  # tools/ runs from anywhere; obs is stdlib-only
    from pytorch_mnist_ddp_tpu.obs.events import read_events
    from pytorch_mnist_ddp_tpu.obs.registry import percentile

    files = sorted(glob.glob(os.path.join(directory, "*.jsonl")))
    events: list[dict] = []
    for path in files:
        events.extend(read_events(path))
    if not events:
        return None

    lines = [
        f"telemetry summary: {directory} "
        f"({len(events)} events, {len(files)} file(s), "
        f"{len({e.get('run_id') for e in events})} run(s))"
    ]
    steps = [e for e in events if e.get("event") == "step"]
    if steps:
        lats = sorted(e["latency_s"] for e in steps if "latency_s" in e)
        if lats:
            lines.append(
                f"  steps: {len(steps)}, "
                f"mean {1e3 * sum(lats) / len(lats):.2f} ms, "
                f"p50 {1e3 * percentile(lats, 50):.2f} ms, "
                f"p95 {1e3 * percentile(lats, 95):.2f} ms"
            )
        else:
            lines.append(f"  steps: {len(steps)} (no latency fields)")
        losses = [e["loss"] for e in steps if e.get("loss") is not None]
        if losses:
            lines.append(
                f"  loss: first {losses[0]:.6f}, last {losses[-1]:.6f}"
            )
    epochs = [e for e in events if e.get("event") == "epoch_train_end"]
    if epochs:
        last = epochs[-1]
        lines.append(
            f"  epochs: {len(epochs)}, last "
            f"{last.get('samples_per_s', 0.0):.1f} samples/s "
            f"({last.get('samples', 0)} samples in "
            f"{last.get('duration_s', 0.0):.2f} s)"
        )
    evals = [e for e in events if e.get("event") == "eval"]
    if evals:
        lines.append(
            f"  eval: {len(evals)} pass(es), final accuracy "
            f"{evals[-1].get('accuracy', 0.0):.4f} "
            f"(avg loss {evals[-1].get('avg_loss', 0.0):.4f})"
        )
    span_ends = [e for e in events if e.get("event") == "span_end"]
    if span_ends:
        by_span: dict[str, list[float]] = {}
        for e in span_ends:
            by_span.setdefault(e.get("span", "?"), []).append(
                e.get("duration_s", 0.0)
            )
        rendered = ", ".join(
            f"{name} x{len(ds)} ({sum(ds):.2f} s)"
            for name, ds in sorted(by_span.items())
        )
        lines.append(f"  spans: {rendered}")
    # Startup section (docs/COMPILE.md): per-program compile durations
    # (the compile service's spans), the measured overlap win, and the
    # serialized-executable store's hit/miss/fallback tallies — the
    # operator's view of what a cold vs warm start actually paid.
    compiles = [
        e for e in events
        if e.get("event") == "span_end" and e.get("span") == "compile"
    ]
    if compiles:
        by_fn: dict[str, list[float]] = {}
        for e in compiles:
            by_fn.setdefault(e.get("fn", "?"), []).append(
                e.get("duration_s", 0.0)
            )
        rendered = ", ".join(
            f"{fn} x{len(ds)} ({sum(ds):.2f} s)"
            for fn, ds in sorted(by_fn.items())
        )
        lines.append(f"  startup compiles: {rendered}")
    overlaps = [e for e in events if e.get("event") == "startup_overlap"]
    if overlaps:
        last = overlaps[-1]
        tasks = last.get("tasks") or {}
        rendered = ", ".join(
            f"{name} {dur:.2f} s" for name, dur in sorted(tasks.items())
        )
        lines.append(
            f"  startup overlap: ratio {last.get('overlap_ratio', 0.0):.2f} "
            f"(wall {last.get('wall_s', 0.0):.2f} s; {rendered})"
        )
    aots = [e for e in events if e.get("event") == "aot_executable"]
    if aots:
        counts: dict[str, int] = {}
        for e in aots:
            counts[e.get("outcome", "?")] = counts.get(e.get("outcome", "?"), 0) + 1
        lines.append(
            "  aot executables: "
            + ", ".join(
                f"{counts.get(k, 0)} {k}" for k in ("hit", "miss", "fallback")
            )
        )
    # Steady-state input pipeline (data/prefetch.py prefetch_epoch
    # events): the device_run_share-style split of consume wall into
    # data wait vs step time, per pipeline — the number ISSUE 6's
    # double-buffered prefetch exists to drive toward zero.
    prefetches = [e for e in events if e.get("event") == "prefetch_epoch"]
    if prefetches:
        by_pipe: dict[str, list[dict]] = {}
        for e in prefetches:
            by_pipe.setdefault(e.get("pipeline", "?"), []).append(e)
        for pipe, evs in sorted(by_pipe.items()):
            batches = sum(e.get("batches", 0) for e in evs)
            wait = sum(e.get("wait_s_total", 0.0) for e in evs)
            wall = sum(e.get("consume_wall_s", 0.0) for e in evs)
            occ = (
                sum(e.get("occupancy_mean", 0.0) * e.get("batches", 0)
                    for e in evs) / batches
                if batches else 0.0
            )
            share = wait / wall if wall > 0 else 0.0
            lines.append(
                f"  steady state [{pipe}]: {batches} batches over "
                f"{len(evs)} epoch(s), data wait {wait:.3f} s of "
                f"{wall:.2f} s consume wall (wait share {share:.1%}, "
                f"step share {1 - share:.1%}), mean buffer occupancy "
                f"{occ:.2f} (depth {evs[-1].get('depth', '?')})"
            )
    # Training resilience (resilience/, docs/ROBUSTNESS.md trainer
    # section): anomalies by kind with the retry/abort split, checkpoint
    # cadence + write durations by reason, stalls, preemptions, resumes,
    # and input-pipeline retries — the operator's receipt of what the
    # run survived.
    anomalies = [e for e in events if e.get("event") == "train_anomaly"]
    checkpoints = [e for e in events if e.get("event") == "checkpoint"]
    ckpt_failures = [e for e in events if e.get("event") == "checkpoint_failed"]
    stalls = [e for e in events if e.get("event") == "train_stall"]
    resumes = [e for e in events if e.get("event") == "train_resume"]
    preempts = [e for e in events if e.get("event") == "preempt_exit"]
    data_retries = [e for e in events if e.get("event") == "data_retry"]
    if (anomalies or checkpoints or ckpt_failures or stalls or resumes
            or preempts or data_retries):
        lines.append(
            f"  training resilience: {len(anomalies)} anomaly(ies), "
            f"{len(checkpoints)} checkpoint(s), {len(stalls)} stall(s), "
            f"{len(resumes)} resume(s), {len(preempts)} preemption(s)"
        )
        if anomalies:
            by_kind: dict[str, int] = {}
            aborted = 0
            for e in anomalies:
                kind = e.get("kind", "?")
                by_kind[kind] = by_kind.get(kind, 0) + 1
                if e.get("action") == "abort":
                    aborted += 1
            lines.append(
                "    anomalies by kind: "
                + ", ".join(
                    f"{kind} x{n}" for kind, n in sorted(by_kind.items())
                )
                + (f"; {aborted} exhausted the retry budget (run aborted)"
                   if aborted else "; all healed by rollback+retry")
            )
        if checkpoints:
            by_reason: dict[str, list] = {}
            for e in checkpoints:
                by_reason.setdefault(e.get("reason", "?"), []).append(e)
            for reason, es in sorted(by_reason.items()):
                durs = [e.get("duration_s", 0.0) for e in es]
                steps = sorted(
                    e["steps_total"] for e in es if "steps_total" in e
                )
                gaps = [b - a for a, b in zip(steps, steps[1:])]
                cadence = (
                    f", cadence {sum(gaps) / len(gaps):.1f} step(s)"
                    if gaps else ""
                )
                lines.append(
                    f"    checkpoints [{reason}]: {len(es)}, mean write "
                    f"{1e3 * sum(durs) / len(durs):.1f} ms{cadence}"
                )
        if ckpt_failures:
            lines.append(
                f"    checkpoint failures (survived): {len(ckpt_failures)} "
                f"(last: {ckpt_failures[-1].get('error', '?')})"
            )
        if stalls:
            ages = [e.get("age_s", 0.0) for e in stalls]
            lines.append(
                f"    stalls: {len(stalls)}, max age {max(ages):.2f} s"
            )
        for e in resumes:
            lines.append(
                f"    resumed: epoch {e.get('epoch', '?')} at batch cursor "
                f"{e.get('batch_cursor', '?')} from {e.get('archive', '?')}"
            )
        for e in preempts:
            lines.append(
                f"    preempted: signal {e.get('signum', '?')} at epoch "
                f"{e.get('epoch', '?')} cursor {e.get('batch_cursor', '?')} "
                f"(exit {e.get('exit_code', '?')})"
            )
        if data_retries:
            by_pipe: dict[str, int] = {}
            for e in data_retries:
                pipe = e.get("pipeline", "?")
                by_pipe[pipe] = by_pipe.get(pipe, 0) + 1
            lines.append(
                "    data retries: "
                + ", ".join(
                    f"{pipe} x{n}" for pipe, n in sorted(by_pipe.items())
                )
            )
    # Distributed resilience (parallel/elastic.py launcher events +
    # parallel/distributed.py rendezvous events, ISSUE 10): rank deaths
    # by rank, gang restarts with time-to-recover, rendezvous attempt
    # statistics — the elastic runtime's survival receipt.
    rank_deaths = [e for e in events if e.get("event") == "rank_death"]
    gang_restarts = [e for e in events if e.get("event") == "gang_restart"]
    gang_exhausted = [e for e in events if e.get("event") == "gang_exhausted"]
    rdzv = [e for e in events if e.get("event") == "rendezvous"]
    rdzv_retries = [e for e in events if e.get("event") == "rendezvous_retry"]
    if rank_deaths or gang_restarts or gang_exhausted or rdzv or rdzv_retries:
        deaths_by_rank: dict[str, int] = {}
        for e in rank_deaths:
            key = str(e.get("rank", "?"))
            deaths_by_rank[key] = deaths_by_rank.get(key, 0) + 1
        attempts = [e.get("attempts", 1) for e in rdzv]
        mean_attempts = (
            sum(attempts) / len(attempts) if attempts else 0.0
        )
        recoveries = [
            e.get("downtime_s", 0.0) for e in gang_restarts
        ]
        mean_recover = (
            sum(recoveries) / len(recoveries) if recoveries else 0.0
        )
        lines.append(
            f"  distributed resilience: {len(rank_deaths)} rank death(s), "
            f"{len(gang_restarts)} gang restart(s), mean rendezvous "
            f"attempts {mean_attempts:.2f}, mean time-to-recover "
            f"{mean_recover:.2f} s"
        )
        if deaths_by_rank:
            lines.append(
                "    rank deaths: "
                + ", ".join(
                    f"rank {r} x{n} "
                    + "("
                    + "/".join(sorted({
                        e.get("reason", "?") for e in rank_deaths
                        if str(e.get("rank", "?")) == r
                    }))
                    + ")"
                    for r, n in sorted(deaths_by_rank.items())
                )
            )
        for e in gang_restarts:
            lines.append(
                f"    gang restart {e.get('attempt', '?')}: backoff "
                f"{e.get('backoff_s', 0.0):.2f} s, downtime "
                f"{e.get('downtime_s', 0.0):.2f} s (rank "
                f"{e.get('rank', '?')} {e.get('reason', '?')})"
            )
        if rdzv_retries:
            lines.append(
                f"    rendezvous retries: {len(rdzv_retries)} "
                f"(last: {rdzv_retries[-1].get('error', '?')})"
            )
        for e in gang_exhausted:
            lines.append(
                f"    gang EXHAUSTED after {e.get('attempts', '?')} "
                f"attempt(s) (budget {e.get('budget', '?')}, rank "
                f"{e.get('rank', '?')} {e.get('reason', '?')})"
            )
    # Serving pipeline telemetry (serving/batcher.py under --telemetry-dir):
    # per-request latency plus per-batch fill/stall — the operator's view
    # of how well the in-flight window is overlapping.
    sreqs = [e for e in events if e.get("event") == "serving_request"]
    if sreqs:
        lats = sorted(e["latency_s"] for e in sreqs if "latency_s" in e)
        if lats:
            lines.append(
                f"  serving: {len(sreqs)} requests, "
                f"p50 {1e3 * percentile(lats, 50):.2f} ms, "
                f"p95 {1e3 * percentile(lats, 95):.2f} ms, "
                f"p99 {1e3 * percentile(lats, 99):.2f} ms"
            )
        by_dtype: dict[str, list[float]] = {}
        for e in sreqs:
            if "latency_s" in e and e.get("dtype"):
                by_dtype.setdefault(e["dtype"], []).append(e["latency_s"])
        if len(by_dtype) > 1:  # per-variant split only when mixed traffic
            for name, ds in sorted(by_dtype.items()):
                ds.sort()
                lines.append(
                    f"    dtype {name}: {len(ds)} requests, "
                    f"p50 {1e3 * percentile(ds, 50):.2f} ms, "
                    f"p99 {1e3 * percentile(ds, 99):.2f} ms"
                )
    # Tail-latency section (serving/qos.py + the router's hedger,
    # docs/SERVING.md): per-QoS-class request percentiles, load-shed
    # counts, and the hedge dispatch/outcome tallies with win rate —
    # the operator's receipt of what the SLO-aware scheduler did.
    sheds = [e for e in events if e.get("event") == "qos_shed"]
    hedge_dispatches = [
        e for e in events if e.get("event") == "hedge_dispatch"
    ]
    hedge_outcomes = [e for e in events if e.get("event") == "hedge_outcome"]
    qos_tagged = any("qos" in e for e in sreqs)
    if qos_tagged or sheds or hedge_dispatches or hedge_outcomes:
        by_qos: dict[str, list[float]] = {}
        for e in sreqs:
            if "latency_s" in e:
                # Schema note (serving/batcher.py): the default class is
                # untagged so pre-QoS JSONL stays byte-stable.
                by_qos.setdefault(e.get("qos", "interactive"), []).append(
                    e["latency_s"]
                )
        shed_by_qos: dict[str, int] = {}
        for e in sheds:
            name = e.get("qos", "?")
            shed_by_qos[name] = shed_by_qos.get(name, 0) + 1
        lines.append(
            f"  tail latency: {sum(len(v) for v in by_qos.values())} "
            f"classed request(s), {len(sheds)} shed, "
            f"{len(hedge_dispatches)} hedge dispatch(es)"
        )
        for name, ds in sorted(by_qos.items()):
            ds.sort()
            lines.append(
                f"    qos {name}: {len(ds)} requests, "
                f"p50 {1e3 * percentile(ds, 50):.2f} ms, "
                f"p95 {1e3 * percentile(ds, 95):.2f} ms, "
                f"p99 {1e3 * percentile(ds, 99):.2f} ms"
                + (f", {shed_by_qos[name]} shed"
                   if shed_by_qos.get(name) else "")
            )
        for name in sorted(set(shed_by_qos) - set(by_qos)):
            lines.append(
                f"    qos {name}: 0 completed, {shed_by_qos[name]} shed"
            )
        if hedge_outcomes:
            tally: dict[str, int] = {}
            for e in hedge_outcomes:
                tally[e.get("outcome", "?")] = (
                    tally.get(e.get("outcome", "?"), 0) + 1
                )
            placed = tally.get("won", 0) + tally.get("lost", 0)
            lines.append(
                f"    hedges: {tally.get('won', 0)} won, "
                f"{tally.get('lost', 0)} lost, "
                f"{tally.get('cancelled', 0)} cancelled"
                + (f"; win rate {tally.get('won', 0) / placed:.1%}"
                   if placed else "")
            )
    # Scale-out telemetry (serving/pool.py + router.py): per-replica
    # request share, router decision tallies by policy, drain/re-add
    # durations, and the load-imbalance ratio (max/mean replica share) —
    # the operator's view of whether the router is actually spreading.
    # Grouped per run_id: the sweep recipe (serve_loadgen
    # --replicas-sweep) accumulates one run per rung in the same
    # directory, and a cross-run merge would read as imbalance (r0
    # serves in every rung, r3 only in the last) even when each rung's
    # router spread perfectly.
    share_runs: dict[object, dict[str, int]] = {}
    for e in sreqs:
        if e.get("replica"):
            tally = share_runs.setdefault(e.get("run_id"), {})
            tally[e["replica"]] = tally.get(e["replica"], 0) + 1
    # A starved replica served nothing, so it has no serving_request
    # events — but it is exactly the replica the imbalance ratio exists
    # to expose.  Count it as 0 if ANY event in the run names it (a
    # replica with no events at all is undiscoverable from JSONL).
    run_replicas: dict[object, set] = {}
    for e in events:
        if e.get("replica"):
            run_replicas.setdefault(e.get("run_id"), set()).add(e["replica"])
    for rid, by_replica in share_runs.items():
        for name in run_replicas.get(rid, ()):
            by_replica.setdefault(name, 0)
        total = sum(by_replica.values())
        mean = total / len(by_replica)
        imbalance = max(by_replica.values()) / mean if mean else 0.0
        shares = ", ".join(
            f"{name} {100.0 * n / total:.1f}% ({n})"
            for name, n in sorted(by_replica.items())
        )
        # run_id = wall-clock prefix + random hex; the TAIL is what
        # tells two runs in one directory apart.
        suffix = f" [run {str(rid)[-6:]}]" if len(share_runs) > 1 else ""
        lines.append(
            f"  scale-out: {len(by_replica)} replica(s), requests by "
            f"replica: {shares}; load imbalance (max/mean) "
            f"{imbalance:.2f}{suffix}"
        )
    decisions = [e for e in events if e.get("event") == "router_decision"]
    if decisions:
        decision_runs: dict[tuple, dict[str, int]] = {}
        for e in decisions:
            tally = decision_runs.setdefault(
                (e.get("run_id"), e.get("policy", "?")), {}
            )
            name = e.get("replica", "?")
            tally[name] = tally.get(name, 0) + 1
        multi = len({rid for rid, _ in decision_runs}) > 1
        for (rid, policy), tally in decision_runs.items():
            rendered = ", ".join(
                f"{name} {n}" for name, n in sorted(tally.items())
            )
            suffix = f" [run {str(rid)[-6:]}]" if multi else ""
            lines.append(
                f"  router decisions [{policy}]: {rendered}{suffix}"
            )
    # Sharded serving (serving/pool.py + engine.py): pool topology by
    # replica shard shape, request share per shape, the warmup parity
    # gates, EP expert-load imbalance, and the cost policy's decision
    # tallies by request shape class — the operator's view of whether
    # heterogeneous replicas (tp4 next to dp) are earning their devices.
    topologies = [e for e in events if e.get("event") == "pool_topology"]
    sharded_topos = [
        e for e in topologies
        if any(r.get("shard_kind", "dp") != "dp"
               for r in e.get("replicas", {}).values())
    ]
    multi_topo = len({e.get("run_id") for e in sharded_topos}) > 1
    for topo in sharded_topos:
        rid = topo.get("run_id")
        replicas = topo.get("replicas", {})
        shape_of = {
            name: f"{r.get('shard_kind', 'dp')}x{r.get('devices', 1)}"
            for name, r in replicas.items()
        }
        rendered = ", ".join(
            f"{name} {shape}" for name, shape in sorted(shape_of.items())
        )
        suffix = f" [run {str(rid)[-6:]}]" if multi_topo else ""
        lines.append(
            f"  sharded pool: {len(replicas)} replica(s) over "
            f"{sum(r.get('devices', 1) for r in replicas.values())} "
            f"device(s): {rendered}{suffix}"
        )
        # Request share folded by SHAPE, not by replica: a tp4 replica
        # holding 4 devices should be judged against the dp replicas'
        # combined share, and the per-replica line above already exists.
        by_replica = share_runs.get(rid, {})
        if by_replica:
            by_shape: dict[str, int] = {}
            for name, n in by_replica.items():
                by_shape[shape_of.get(name, "dpx1")] = (
                    by_shape.get(shape_of.get(name, "dpx1"), 0) + n
                )
            total = sum(by_shape.values())
            shares = ", ".join(
                f"{shape} {100.0 * n / total:.1f}% ({n})"
                for shape, n in sorted(by_shape.items())
            )
            lines.append(
                f"    requests by replica shape: {shares}{suffix}"
            )
    for e in events:
        if e.get("event") != "expert_load":
            continue
        loads = e.get("loads", {})
        imbalance = e.get("imbalance")
        rendered = ", ".join(
            f"e{k} {v:.0f}" for k, v in sorted(loads.items())
        )
        lines.append(
            "  expert load (final EP dispatch): " + rendered
            + (f"; imbalance (max/mean) {imbalance:.2f}"
               if imbalance is not None else "")
        )
    shaped = [
        e for e in decisions if e.get("shape_class")
    ]
    if shaped:
        shape_runs: dict[tuple, dict[str, int]] = {}
        for e in shaped:
            tally = shape_runs.setdefault(
                (e.get("run_id"), e.get("policy", "?")), {}
            )
            cls = e.get("shape_class", "?")
            tally[cls] = tally.get(cls, 0) + 1
        multi = len({rid for rid, _ in shape_runs}) > 1
        for (rid, policy), tally in shape_runs.items():
            rendered = ", ".join(
                f"{cls} {n}" for cls, n in sorted(
                    tally.items(),
                    key=lambda kv: int(kv[0][1:])
                    if kv[0][1:].isdigit() else 0,
                )
            )
            suffix = f" [run {str(rid)[-6:]}]" if multi else ""
            lines.append(
                f"  shape-class decisions [{policy}]: {rendered}{suffix}"
            )

    def _elastic_lines(kind: str, label: str) -> None:
        # Same per-run grouping as the share/decision lines above.
        ev_runs: dict[object, list] = {}
        for e in events:
            if e.get("event") == kind:
                ev_runs.setdefault(e.get("run_id"), []).append(e)
        for rid, es in ev_runs.items():
            rendered = ", ".join(
                f"{e.get('replica', '?')} {e.get('duration_s', 0.0):.3f} s"
                for e in es
            )
            suffix = f" [run {str(rid)[-6:]}]" if len(ev_runs) > 1 else ""
            lines.append(f"  {label}: {rendered}{suffix}")

    _elastic_lines("replica_drain", "replica drains")
    _elastic_lines("replica_add", "replica re-adds")
    # Resilience section (serving/faults.py + the pool supervisor,
    # docs/ROBUSTNESS.md): quarantines by reason, restarts per replica
    # with mean recovery time (quarantine -> routable again), circuit
    # open/half-open transitions, ejections, and the transparent-retry
    # tally — the operator's view of what the chaos (or production
    # faults) actually cost.
    quarantines = [e for e in events if e.get("event") == "replica_quarantine"]
    restarts = [
        e for e in events
        if e.get("event") == "replica_restart"
        and e.get("outcome") == "restarted"
    ]
    ejections = [e for e in events if e.get("event") == "replica_eject"]
    transitions = [e for e in events if e.get("event") == "circuit_transition"]
    retries = [e for e in events if e.get("event") == "request_retry"]
    if quarantines or restarts or ejections or transitions or retries:
        lines.append(
            f"  resilience: {len(quarantines)} quarantine(s), "
            f"{len(restarts)} restart(s), {len(ejections)} ejection(s), "
            f"{len(retries)} retry(ies)"
        )
        if restarts:
            by_replica: dict[str, int] = {}
            for e in restarts:
                name = e.get("replica", "?")
                by_replica[name] = by_replica.get(name, 0) + 1
            recoveries = [
                e["recovery_s"] for e in restarts if "recovery_s" in e
            ]
            rendered = ", ".join(
                f"{name} x{n}" for name, n in sorted(by_replica.items())
            )
            lines.append(
                f"    restarts by replica: {rendered}"
                + (f" (mean recovery "
                   f"{sum(recoveries) / len(recoveries):.3f} s)"
                   if recoveries else "")
            )
        if quarantines:
            by_reason: dict[str, int] = {}
            for e in quarantines:
                reason = e.get("reason", "?")
                by_reason[reason] = by_reason.get(reason, 0) + 1
            lines.append(
                "    quarantines by reason: "
                + ", ".join(
                    f"{reason} x{n}"
                    for reason, n in sorted(by_reason.items())
                )
            )
        if transitions:
            per_replica: dict[str, dict[str, int]] = {}
            for e in transitions:
                tally = per_replica.setdefault(e.get("replica", "?"), {})
                dst = e.get("dst", "?")
                tally[dst] = tally.get(dst, 0) + 1
            for name, tally in sorted(per_replica.items()):
                rendered = ", ".join(
                    f"->{dst} x{n}"
                    # Stable lifecycle order, not alphabetical: the
                    # open -> half-open -> closed story reads forward.
                    for dst in ("open", "half-open", "closed")
                    if (n := tally.get(dst))
                )
                lines.append(f"    circuit transitions [{name}]: {rendered}")
        for e in ejections:
            lines.append(
                f"    ejected: {e.get('replica', '?')} "
                f"({e.get('reason', '?')}, after {e.get('attempts', '?')} "
                "restart(s))"
            )
    # Fleet section (serving/fleet.py, docs/SERVING.md fleet tier):
    # per-backend placement share with the load-imbalance ratio, the
    # autoscaler's event timeline, and mean backend-replacement time
    # (incident -> serving again) — the operator's receipt of what the
    # fleet control plane did.  Grouped per run_id like the scale-out
    # lines: a sweep accumulates one run per rung in one directory.
    froutes = [e for e in events if e.get("event") == "fleet_route"]
    fdeaths = [e for e in events if e.get("event") == "backend_death"]
    freplaces = [e for e in events if e.get("event") == "backend_replace"]
    fejects = [e for e in events if e.get("event") == "backend_eject"]
    fdrains = [e for e in events if e.get("event") == "backend_drain"]
    fscales = [e for e in events if e.get("event") == "fleet_scale"]
    if froutes or fdeaths or freplaces or fscales or fdrains or fejects:
        lines.append(
            f"  fleet: {len(froutes)} placement(s), {len(fdeaths)} "
            f"backend death(s), {len(freplaces)} replacement(s), "
            f"{len(fscales)} scale event(s), {len(fdrains)} drain-down(s)"
        )
        fshare_runs: dict[object, dict[str, int]] = {}
        for e in froutes:
            tally = fshare_runs.setdefault(e.get("run_id"), {})
            name = e.get("backend", "?")
            tally[name] = tally.get(name, 0) + 1
        for rid, tally in fshare_runs.items():
            total = sum(tally.values())
            mean = total / len(tally)
            imbalance = max(tally.values()) / mean if mean else 0.0
            shares = ", ".join(
                f"{name} {100.0 * n / total:.1f}% ({n})"
                for name, n in sorted(tally.items())
            )
            suffix = (
                f" [run {str(rid)[-6:]}]" if len(fshare_runs) > 1 else ""
            )
            lines.append(
                f"    backend share: {shares}; imbalance (max/mean) "
                f"{imbalance:.2f}{suffix}"
            )
        if freplaces:
            downs = [e.get("downtime_s", 0.0) for e in freplaces]
            by_backend: dict[str, int] = {}
            for e in freplaces:
                name = e.get("backend", "?")
                by_backend[name] = by_backend.get(name, 0) + 1
            lines.append(
                "    replacements: "
                + ", ".join(
                    f"{name} x{n}" for name, n in sorted(by_backend.items())
                )
                + f" (mean replacement {sum(downs) / len(downs):.2f} s)"
            )
        if fscales:
            # Timeline relative to each run's first event, so the
            # up/down story reads in run seconds, not epoch ts.
            run_t0: dict[object, float] = {}
            for e in events:
                rid = e.get("run_id")
                ts = e.get("ts")
                if ts is None:
                    continue
                if rid not in run_t0 or ts < run_t0[rid]:
                    run_t0[rid] = ts
            for e in fscales:
                rel = e.get("ts", 0.0) - run_t0.get(e.get("run_id"), 0.0)
                lines.append(
                    f"    scale {e.get('direction', '?')} at +{rel:.1f}s: "
                    f"{e.get('backends', '?')} backend(s), "
                    f"{e.get('kind', 'depth')} signal "
                    f"{e.get('signal', 0.0):.2f}"
                )
        for e in fejects:
            lines.append(
                f"    ejected: {e.get('backend', '?')} "
                f"({e.get('reason', '?')}, after {e.get('attempts', '?')} "
                "attempt(s))"
            )
    # Host path section (serving/wire.py + serving/cache.py,
    # docs/SERVING.md): the response cache's served-from-cache tally by
    # tier (admission point vs fleet front), invalidations, and any
    # wire_fallback breadcrumbs — a client that THINKS it speaks binary
    # but typo'd the content type shows up here, not as a silent
    # latency regression.
    chits = [e for e in events if e.get("event") == "cache_hit"]
    cinvs = [e for e in events if e.get("event") == "cache_invalidate"]
    wfalls = [e for e in events if e.get("event") == "wire_fallback"]
    if chits or cinvs or wfalls:
        by_scope: dict[str, int] = {}
        for e in chits:
            scope = e.get("scope", "server")
            by_scope[scope] = by_scope.get(scope, 0) + 1
        scopes = ", ".join(
            f"{n} at the {scope}"
            for scope, n in sorted(by_scope.items())
        ) or "0"
        lines.append(
            f"  host path: {len(chits)} cache hit(s) ({scopes}), "
            f"{len(cinvs)} invalidation(s), {len(wfalls)} wire "
            "fallback(s)"
        )
        if wfalls:
            types: dict[str, int] = {}
            for e in wfalls:
                ct = e.get("content_type", "?")
                types[ct] = types.get(ct, 0) + 1
            lines.append(
                "    fallback content types: "
                + ", ".join(
                    f"{ct} x{n}" for ct, n in sorted(types.items())
                )
            )
    gates = [e for e in events if e.get("event") == "parity_gate"]
    if gates:
        for e in gates:
            # Sharded warmup gates (engine.verify_sharded_parity) carry
            # the replica's shard shape next to the dtype variant label.
            label = str(e.get("dtype", "?"))
            if e.get("shard_kind") and e.get("shard_kind") != "dp":
                label += f" {e['shard_kind']}x{e.get('devices', '?')}"
            lines.append(
                f"  parity gate [{label}]: "
                + ("PASS" if e.get("passed") else "FAIL")
                + f" (max|dlogit| {e.get('max_abs_logit_diff', 0.0):.2e}"
                f" <= {e.get('tolerance', 0.0):g}, argmax_identical="
                f"{e.get('argmax_identical')})"
            )
    sbatches = [e for e in events if e.get("event") == "serving_batch"]
    if sbatches:
        fills = [e["fill_ratio"] for e in sbatches if "fill_ratio" in e]
        stalls = [e.get("stall_s", 0.0) for e in sbatches]
        stalled = [s for s in stalls if s > 0]
        lines.append(
            f"  serving batches: {len(sbatches)}, mean fill "
            f"{100.0 * sum(fills) / len(fills):.1f}%, "
            f"{len(stalled)} stalled dispatches "
            f"({sum(stalls):.3f} s total stall)"
            if fills else
            f"  serving batches: {len(sbatches)}"
        )
    # Device path section (PR 19, docs/SERVING.md packed batching): the
    # packed-vs-bucketed split of what the DEVICE was fed.  Packed
    # dispatches are tagged on the serving_batch event; fill here is
    # live rows over the rows-capacity the device computed, and the
    # warmup-executable tally per mode comes from the compile spans of
    # the runs that produced each mode's batches — the two numbers the
    # packed ladder collapse exists to move (fill up, executables down).
    packed_batches = [e for e in sbatches if e.get("packed")]
    if packed_batches:
        def _mode_line(label, evs):
            fills = [e["fill_ratio"] for e in evs if "fill_ratio" in e]
            pad = sum(
                e["bucket"] - e["real"] for e in evs
                if "bucket" in e and "real" in e
            )
            caps = sorted({e["bucket"] for e in evs if e.get("bucket")})
            rids = {e.get("run_id") for e in evs}
            execs = sum(
                1 for e in events
                if e.get("event") == "span_end"
                and e.get("span") == "compile"
                and e.get("run_id") in rids
            )
            return (
                f"    {label}: {len(evs)} dispatch(es), mean fill "
                f"{100.0 * sum(fills) / len(fills):.1f}%, "
                f"{pad} padding row(s), "
                f"capacities {'/'.join(str(c) for c in caps) or '?'}"
                + (f", {execs} warmup executable(s)" if execs else "")
            )

        bucketed_batches = [e for e in sbatches if not e.get("packed")]
        lines.append(
            f"  device path: {len(packed_batches)} packed of "
            f"{len(sbatches)} dispatch(es)"
        )
        lines.append(_mode_line("packed", packed_batches))
        if bucketed_batches:
            lines.append(_mode_line("bucketed", bucketed_batches))
    runs = [e for e in events if e.get("event") == "run_complete"]
    if runs:
        # Correctly-labeled seconds — the telemetry surface does NOT
        # inherit the stdout line's "ms" label quirk (utils/logging.py).
        lines.append(
            f"  run wall: {runs[-1].get('wall_seconds', 0.0):.2f} s"
        )
    return "\n".join(lines)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--no-write", action="store_true")
    p.add_argument(
        "--telemetry", metavar="DIR", default=None,
        help="summarize a --telemetry-dir JSONL directory instead of the "
        "bench artifacts (stdout only, never writes PERF.md)",
    )
    args = p.parse_args()
    if args.telemetry:
        summary = summarize_telemetry(args.telemetry)
        if summary is None:
            print(
                f"perf_report: no parseable *.jsonl events in "
                f"{args.telemetry}", file=sys.stderr,
            )
            return 1
        print(summary)
        return 0
    report = build_report()
    if report is None:
        print("perf_report: no ladder artifact (bench_r*_stepattr.json) "
              "yet", file=sys.stderr)
        return 1
    print(report)
    if not args.no_write:
        with open(PERF_MD, "a") as f:
            f.write("\n" + report + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
