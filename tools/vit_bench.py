"""ViT-family hardware benchmark: one JSON line per run.

The headline bench (bench.py) measures the reference CNN protocol; this
tool records the beyond-parity attention family on the same protocol
shape — ``vit_mnist.py --fused --epochs 20 --batch-size 200`` — with the
SAME attribution contract as bench.py (round-3 verdict item 4):
``run_s`` / ``compile_s`` / ``data_s`` via the CLI's ``--timings-json``
AOT split, steady-state images/sec over ``run_s``, and MFU from the
analytic ViT FLOPs model (utils/flops.py:vit_run_flops).

``--mode sp|tp|flash`` instead records a parallel-mode smoke row (every
shipped mode gets at least one hardware number) — per-batch paths with
no single compiled program, so those rows carry wall clock + accuracy
only.  ``--mode zero`` rides the fused whole-run (the round-5 ZeRO
composition), so its row carries the full attribution too.

Run by tools/tunnel_watch.sh in accelerator windows; results land in
``bench_r5_vit*.json`` via the watcher's min-by-value promotion.

Usage: python tools/vit_bench.py [--mode M] [--epochs N] [--batch-size N]
Prints ONE JSON line on stdout; exit 1 with an error JSON on failure.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Extra CLI flags per smoke mode.  One chip is visible on this host, so
# the sp/tp/pp rows ride --allow-degree-1: the REAL parallel code paths
# (shard_map programs, ring/all_to_all/ppermute collectives, the GPipe
# engine) compile and execute on a 1-wide axis — the row records
# degree 1 so the reduced claim is explicit.
_MODES = {
    "fused": ["--fused"],
    "sp": ["--sp", "1", "--allow-degree-1"],
    "sp-ulysses": ["--sp", "1", "--sp-impl", "ulysses", "--allow-degree-1"],
    "tp": ["--tp", "1", "--allow-degree-1"],
    # no "pp": the GPipe engine is structurally >= 2 stages and one chip
    # is visible — its hardware row needs a multi-chip window.
    "flash": ["--flash"],
    # ZeRO-1 rides the fused whole-run (round-5 composition), so its row
    # carries the full run_s/compile_s/data_s attribution like "fused".
    "zero": ["--zero", "--fused"],
}

# Modes that run the fused whole-run and therefore support the
# --timings-json AOT attribution contract.
_FUSED_MODES = ("fused", "zero")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--mode", default="fused", choices=sorted(_MODES))
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=200)
    p.add_argument("--test-batch-size", type=int, default=1000)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args()
    metric = f"vit_mnist_{args.mode}_wall_clock"

    def fail(reason: str) -> int:
        print(json.dumps({"metric": metric, "value": None, "error": reason}))
        return 1

    # Chip count first (own subprocess — this tool never imports jax):
    # --batch-size is PER SHARD (vit_mnist.py multiplies by the data-axis
    # width), so the recorded row must say how many chips multiplied it.
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); "
             "print(len(d)); print(d[0].device_kind)"],
            capture_output=True, text=True, timeout=120,
        )
        lines = probe.stdout.strip().splitlines()
        n_chips, device_kind = int(lines[-2]), lines[-1]
    except Exception as e:  # dead tunnel, import error, timeout
        return fail(f"device probe failed: {e}")

    cmd = [
        sys.executable, os.path.join(REPO, "vit_mnist.py"),
        "--epochs", str(args.epochs), "--batch-size", str(args.batch_size),
        "--test-batch-size", str(args.test_batch_size),
    ] + _MODES[args.mode]
    timings_path = None
    if args.mode in _FUSED_MODES:
        fd, timings_path = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        cmd += ["--timings-json", timings_path]

    def cleanup_tmp():
        # Every exit path must drop the tempfile — the watcher reruns
        # this tool each window for the round's lifetime.
        if timings_path and os.path.exists(timings_path):
            try:
                os.unlink(timings_path)
            except OSError:
                pass

    start = time.time()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=args.timeout
        )
    except subprocess.TimeoutExpired:
        cleanup_tmp()
        return fail(f"timeout after {args.timeout}s")
    finally:
        wall = time.time() - start
    if proc.returncode != 0:
        cleanup_tmp()
        return fail(f"exit {proc.returncode}: {proc.stderr[-400:]}")

    # The CLI's own wall clock (the reference timer quirk prints seconds
    # under an "ms" label) is authoritative; subprocess wall is the guard.
    m = re.search(r"Total cost time:([0-9.]+)", proc.stdout)
    accs = re.findall(r"Accuracy: (\d+)/(\d+)", proc.stdout)
    if not m or not accs:
        cleanup_tmp()
        return fail("output missing timer or accuracy lines")
    out = proc.stdout + proc.stderr
    final = 100.0 * int(accs[-1][0]) / int(accs[-1][1])
    first = 100.0 * int(accs[0][0]) / int(accs[0][1])
    result = {
        "metric": metric,
        "value": round(float(m.group(1)), 2),
        "unit": "s",
        "model": "vit",
        "mode": args.mode,
        "mode_degree": 1 if "--allow-degree-1" in _MODES[args.mode] else None,
        "epochs": args.epochs,
        "n_chips": n_chips,
        "batch_size_per_shard": args.batch_size,
        "global_batch": args.batch_size * n_chips,
        # Provenance: the fused/zero modes overwrite this below from the
        # timings JSON's authoritative "dataset" field; the per-batch
        # smoke modes infer from the run's own notices (mirroring
        # data/mnist.py's three-way labeling).
        "dataset": (
            "synthetic"
            if "synthetic MNIST-like data" in out
            else "idx-unverified" if "idx-unverified" in out else "idx"
        ),
        "subprocess_wall_s": round(wall, 2),
        "epoch1_test_accuracy": round(first, 2),
        "final_test_accuracy": round(final, 2),
    }
    if timings_path:
        try:
            with open(timings_path) as f:
                t = json.load(f)
        except (OSError, ValueError):
            t = {}
        finally:
            cleanup_tmp()
        if t.get("dataset"):
            # The CLI recorded the loader's own provenance label — more
            # reliable than the notice scrape above.
            result["dataset"] = t["dataset"]
        if "run_s" in t:
            result["run_s"] = round(t["run_s"], 2)
            result["compile_s"] = round(t.get("compile_s", 0.0), 2)
            result["data_s"] = round(t.get("data_s", 0.0), 2)
            result["device_run_share"] = round(
                t["run_s"] / result["value"], 3
            )
            # Heuristic, unlike bench.py's cache-dir diff: a warm load of
            # this program measures ~1-2 s, a cold compile ~20 s.
            result["cache"] = "warm" if t["compile_s"] < 5.0 else "cold"
            if t["run_s"] > 0:
                result["images_per_sec_per_chip_run"] = round(
                    t["train_size"] * args.epochs / t["run_s"] / n_chips, 1
                )
                sys.path.insert(0, REPO)
                from pytorch_mnist_ddp_tpu.models.vit import ViTConfig
                from pytorch_mnist_ddp_tpu.utils.flops import (
                    tpu_peak_flops_per_chip,
                    vit_run_flops,
                )

                cfg = ViTConfig(depth=t.get("depth", 2),
                                dim=t.get("dim", 64))
                flops = vit_run_flops(
                    cfg, t["train_size"], t["test_size"], args.epochs
                )
                peak = tpu_peak_flops_per_chip(device_kind)
                result["model_tflops"] = round(flops / 1e12, 3)
                if peak is not None:
                    result["peak_bf16_tflops_per_chip"] = round(peak / 1e12, 1)
                    result["mfu"] = round(
                        flops / t["run_s"] / (peak * n_chips), 5
                    )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
