"""ViT-family hardware benchmark: one JSON line from a fused whole run.

The headline bench (bench.py) measures the reference CNN protocol; this
tool records the beyond-parity attention family on the same protocol
shape — ``vit_mnist.py --fused --epochs 20 --batch-size 200`` — so the
family has measured (not just tested) hardware behavior.  Run by
tools/tunnel_watch.sh in accelerator windows; results land in
``bench_r3_vit.json`` via the watcher's min-by-value promotion.

Usage: python tools/vit_bench.py [--epochs N] [--batch-size N] [--timeout S]
Prints ONE JSON line on stdout; exit 1 with an error JSON on failure.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=200)
    p.add_argument("--test-batch-size", type=int, default=1000)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args()

    # Chip count first (own subprocess — this tool never imports jax):
    # --batch-size is PER SHARD (vit_mnist.py multiplies by the data-axis
    # width), so the recorded row must say how many chips multiplied it.
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=120,
        )
        n_chips = int(probe.stdout.strip().splitlines()[-1])
    except Exception as e:  # dead tunnel, import error, timeout
        print(json.dumps({
            "metric": "vit_mnist_fused_wall_clock", "value": None,
            "error": f"device probe failed: {e}",
        }))
        return 1

    cmd = [
        sys.executable, os.path.join(REPO, "vit_mnist.py"), "--fused",
        "--epochs", str(args.epochs), "--batch-size", str(args.batch_size),
        "--test-batch-size", str(args.test_batch_size),
    ]
    start = time.time()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=args.timeout
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "metric": "vit_mnist_fused_wall_clock", "value": None,
            "error": f"timeout after {args.timeout}s",
        }))
        return 1
    wall = time.time() - start
    if proc.returncode != 0:
        print(json.dumps({
            "metric": "vit_mnist_fused_wall_clock", "value": None,
            "error": f"exit {proc.returncode}: {proc.stderr[-400:]}",
        }))
        return 1

    # The CLI's own wall clock (the reference timer quirk prints seconds
    # under an "ms" label) is authoritative; subprocess wall is the guard.
    m = re.search(r"Total cost time:([0-9.]+)", proc.stdout)
    accs = re.findall(r"Accuracy: (\d+)/(\d+)", proc.stdout)
    if not m or not accs:
        print(json.dumps({
            "metric": "vit_mnist_fused_wall_clock", "value": None,
            "error": "output missing timer or accuracy lines",
        }))
        return 1
    final = 100.0 * int(accs[-1][0]) / int(accs[-1][1])
    first = 100.0 * int(accs[0][0]) / int(accs[0][1])
    print(json.dumps({
        "metric": "vit_mnist_fused_wall_clock",
        "value": round(float(m.group(1)), 2),
        "unit": "s",
        "model": "vit",
        "epochs": args.epochs,
        "n_chips": n_chips,
        "batch_size_per_shard": args.batch_size,
        "global_batch": args.batch_size * n_chips,
        "dataset": "synthetic"
        if "synthetic MNIST-like data" in (proc.stdout + proc.stderr)
        else "idx",
        "subprocess_wall_s": round(wall, 2),
        "epoch1_test_accuracy": round(first, 2),
        "final_test_accuracy": round(final, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
