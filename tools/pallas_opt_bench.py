"""Micro-benchmark: the three Adadelta update paths, head to head on TPU.

Times N chained steps of each implementation over the real model's
parameter pytree (models/net.py shapes, ~1.2M params):

- ``plain``        — per-leaf XLA update (ops/adadelta.py), the current
                     measured-best default;
- ``pallas_ravel`` — the round-2 kernel: ravel params+grads+state every
                     step (ops/pallas_adadelta.py:adadelta_update_pallas);
- ``pallas_flat``  — the round-3 kernel: accumulators persist in the
                     padded [rows,128] layout, only grads ravel / delta
                     unravel per step (adadelta_update_flat).

Each variant is one jitted ``lax.scan`` over the steps (so per-step python
dispatch doesn't pollute the comparison), timed after a warmup call, with
host-materialized output inside the window (block_until_ready can return
early through the remote tunnel — trainer.py run_s discussion).  Prints
one JSON line with per-step microseconds for each variant — the decision
record the verdict asked for (round-2 weak #6 / next-round item 7).

Run on real TPU (a tunnel window); falls back to CPU+interpret only with
--allow-cpu (orders of magnitude slower, sanity only).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Invoked as ``python tools/pallas_opt_bench.py``: sys.path[0] is tools/,
# so put the repo root (the package's home) ahead of it.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 200


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=STEPS)
    ap.add_argument("--allow-cpu", action="store_true")
    opts = ap.parse_args()

    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    if backend != "tpu" and not opts.allow_cpu:
        print(json.dumps({"error": f"backend {backend!r}; pass --allow-cpu "
                          "to run interpret-mode sanity timings"}))
        sys.exit(1)

    from pytorch_mnist_ddp_tpu.models.net import init_params
    from pytorch_mnist_ddp_tpu.ops.adadelta import adadelta_init, adadelta_update
    from pytorch_mnist_ddp_tpu.ops.pallas_adadelta import (
        adadelta_init_flat,
        adadelta_update_flat,
        adadelta_update_pallas,
    )

    params = init_params(jax.random.PRNGKey(0))
    grads = jax.tree.map(lambda p: jnp.full(p.shape, 1e-3, p.dtype), params)
    interpret = backend != "tpu"

    def scan_of(update, state0):
        def body(carry, _):
            p, s = carry
            p, s = update(p, grads, s, 0.7)
            return (p, s), ()

        def run(p, s):
            (p, s), _ = jax.lax.scan(body, (p, s), None, length=opts.steps)
            return p

        return jax.jit(run), state0

    variants = {
        "plain": scan_of(adadelta_update, adadelta_init(params)),
        "pallas_ravel": scan_of(
            lambda p, g, s, lr: adadelta_update_pallas(
                p, g, s, lr, interpret=interpret
            ),
            adadelta_init(params),
        ),
        "pallas_flat": scan_of(
            lambda p, g, s, lr: adadelta_update_flat(
                p, g, s, lr, interpret=interpret
            ),
            adadelta_init_flat(params),
        ),
    }

    result: dict = {
        "metric": "adadelta_step_us",
        "steps": opts.steps,
        "backend": backend,
        "device_kind": jax.devices()[0].device_kind,
    }
    for name, (run, state0) in variants.items():
        out = run(params, state0)  # warmup: trace + compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = run(params, state0)
        # D2H read, not block_until_ready: see module docstring.
        float(jax.tree.leaves(out)[0].ravel()[0])
        dt = time.perf_counter() - t0
        result[name] = round(dt / opts.steps * 1e6, 2)
    fastest = min(v for k, v in result.items() if isinstance(v, float))
    result["winner"] = next(
        k for k, v in result.items()
        if isinstance(v, float) and v == fastest and k != "steps"
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
