"""Decompose the fused training step's ~0.8 ms/step on real hardware.

Round-3 verdict item 1: the headline warm steady state (`run_s` ~5.2 s
for 6000 steps + eval) sits ~10x above compute-bound and nothing in the
repo says where the time goes.  tools/trace_attr.py answers that from a
profiler trace; this tool answers it by CONSTRUCTION — it times a ladder
of step variants, each a warm jitted ``lax.scan`` over one epoch's worth
of steps (300 at the protocol batch 200), so consecutive rungs isolate
one ingredient:

    empty_scan     scan + int carry only            -> loop overhead
    gather_norm    + batch gather & normalize        -> input cost
    gather_epoch   one pre-permuted epoch gather +   -> the candidate
                   contiguous slices                    input optimization
    fwd            + forward & loss (fixed batch)    -> forward compute
    fwd_bwd        + value_and_grad                  -> backward compute
    full_nodrop    + pmean + Adadelta, dropout off   -> optimizer cost
    full           the real step (dropout on)        -> dropout/RNG cost
    full_nogather  full minus gather (fixed batch)   -> cross-check
    full_pregather full with the epoch-pregather     -> end-to-end win
                   input path                           estimate

Differences between adjacent rungs attribute the per-step budget; the
`full` rung should reproduce bench.py's measured per-step time (run_s /
steps) — if it doesn't, the gap is OUTSIDE the step program (per-epoch
eval, epoch-boundary overhead, D2H of the loss traces).

Prints ONE JSON line; run by tools/tunnel_watch.sh in tunnel windows.
Usage: python tools/step_attr_bench.py [--steps N] [--batch N] [--reps N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The known rung-name set, exported for consumers that must tell rungs
# from metadata WITHOUT importing jax: tools/window_promote.py counts
# measured rungs against exactly this set, so a future top-level float
# metadata key (elapsed_s, budget_s, ...) can never inflate a truncated
# partial's rung count past a more complete committed baseline.  Keep in
# sync with the variants dict in main() (asserted there).
RUNG_NAMES = (
    "full",
    "fwd_bwd",
    "full_nogather",
    "full_pregather",
    "gather_norm",
    "empty_scan",
    "gather_epoch",
    "full_nodrop",
    "fwd",
    "eval",
)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=200)
    p.add_argument("--eval-batch", type=int, default=1000)
    p.add_argument("--eval-steps", type=int, default=10)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--bf16", action="store_true",
                   help="run the ladder at the --bf16 compute dtype")
    p.add_argument("--conv-impl", type=str, default="conv",
                   choices=["conv", "im2col_c1", "im2col"],
                   help="run the ladder with a GEMM-lowered conv variant "
                        "(models/net.py CONV_IMPLS) — isolates conv1's "
                        "MXU-untileable C_in=1 contraction (docs/PERF.md)")
    p.add_argument("--allow-cpu", action="store_true")
    p.add_argument("--only", type=str, default=None,
                   help="comma-separated rung names to run (e.g. "
                        "'full,fwd_bwd'); unknown names are an error. "
                        "Used by the watcher's batch-scaling leg, which "
                        "needs one rung, not ten cold compiles")
    p.add_argument("--budget-s", type=float, default=540.0,
                   help="soft time budget: once exceeded, remaining rungs "
                        "are skipped and the partial JSON still prints "
                        "(must sit below the watcher's 600 s SIGTERM)")
    args = p.parse_args()

    import jax

    jax.config.update("jax_default_prng_impl", "rbg")  # the bench's RNG

    import jax.numpy as jnp
    import numpy as np

    backend = jax.default_backend()
    if backend == "cpu" and not args.allow_cpu:
        print(json.dumps({
            "metric": "step_attr_us", "error": "cpu backend (no TPU)",
        }))
        return 1

    from pytorch_mnist_ddp_tpu.models.net import Net, init_params
    from pytorch_mnist_ddp_tpu.ops.adadelta import adadelta_init, adadelta_update
    from pytorch_mnist_ddp_tpu.ops.loss import nll_loss
    from pytorch_mnist_ddp_tpu.parallel.fused import _normalize_dev
    from pytorch_mnist_ddp_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    compute_dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = Net(compute_dtype=compute_dtype, conv_impl=args.conv_impl)
    params = init_params(jax.random.PRNGKey(0))
    opt = adadelta_init(params)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randint(0, 256, (60000, 28, 28), dtype=np.uint8))
    labels = jnp.asarray(rng.randint(0, 10, 60000).astype(np.int32))
    # Tiled modulo the dataset so steps*batch > 60000 wraps (the fused
    # path's semantics) instead of dying on a reshape error (round-4
    # advisor); for steps*batch <= 60000 this is exactly the old slice.
    idx = np.arange(args.steps * args.batch) % 60000
    perm = jnp.asarray(
        rng.permutation(60000)[idx].reshape(args.steps, args.batch)
    )
    fixed_x = _normalize_dev(images[: args.batch], compute_dtype)
    fixed_y = labels[: args.batch]
    w = jnp.ones((args.batch,), jnp.float32)
    key = jax.random.PRNGKey(1)
    lr = jnp.float32(1.0)

    # Each variant: scan body over `steps` iterations.  The carry always
    # includes a live f32 accumulator folded from the body's result so no
    # rung is dead-code-eliminated.

    def loss_of(params, x, y, dropout_key=None):
        if dropout_key is None:
            logp = model.apply({"params": params}, x, train=False)
        else:
            logp = model.apply({"params": params}, x, train=True,
                               rngs={"dropout": dropout_key})
        return nll_loss(logp, y, w, reduction="mean")

    def make_empty():
        def body(carry, i):
            return carry + 1, ()
        return lambda: jax.lax.scan(body, jnp.int32(0),
                                    jnp.arange(args.steps))[0]

    def make_gather_norm():
        def body(carry, idx):
            x = _normalize_dev(jnp.take(images, idx, axis=0), compute_dtype)
            y = jnp.take(labels, idx, axis=0)
            return carry + x.sum() + y.sum(), ()
        return lambda: jax.lax.scan(body, jnp.float32(0.0), perm)[0]

    def make_gather_epoch():
        # The candidate optimization: ONE permuted gather of the whole
        # epoch up front, then contiguous dynamic slices per step —
        # trades 300 random-row gathers for 1 big gather + cheap slices.
        # Identical samples in identical order (bit-identical batches).
        flat_perm = perm.reshape(-1)

        def run():
            ep_x = jnp.take(images, flat_perm, axis=0)
            ep_y = jnp.take(labels, flat_perm, axis=0)

            def body(carry, i):
                x = _normalize_dev(jax.lax.dynamic_slice_in_dim(
                    ep_x, i * args.batch, args.batch), compute_dtype)
                y = jax.lax.dynamic_slice_in_dim(ep_y, i * args.batch,
                                                 args.batch)
                return carry + x.sum() + y.sum(), ()

            return jax.lax.scan(body, jnp.float32(0.0),
                                jnp.arange(args.steps))[0]
        return run

    def make_fwd():
        def body(carry, i):
            # carry-dependent input: a loop-INVARIANT body would be
            # hoisted out of the scan and time ~0 (observed on CPU).
            x = fixed_x + carry * jnp.float32(1e-30)
            return carry + loss_of(params, x, fixed_y), ()
        return lambda: jax.lax.scan(body, jnp.float32(0.0),
                                    jnp.arange(args.steps))[0]

    def make_fwd_bwd():
        def body(carry, i):
            x = fixed_x + carry * jnp.float32(1e-30)  # see make_fwd
            loss, grads = jax.value_and_grad(loss_of)(params, x, fixed_y)
            acc = carry + loss + jax.tree.leaves(grads)[0].sum()
            return acc, ()
        return lambda: jax.lax.scan(body, jnp.float32(0.0),
                                    jnp.arange(args.steps))[0]

    def make_eval():
        # One epoch's eval: eval-steps batches of eval-batch contiguous
        # rows, masked-sum loss + correct count — mirrors the fused
        # local_eval body so run_s can be reconstructed as
        # steps*full + evals*eval (per epoch).
        def body(carry, i):
            loss_sum, correct = carry
            start = i * args.eval_batch
            x = _normalize_dev(jax.lax.dynamic_slice_in_dim(
                images, start, args.eval_batch), compute_dtype)
            y = jax.lax.dynamic_slice_in_dim(labels, start, args.eval_batch)
            logp = model.apply({"params": params}, x, train=False)
            wv = jnp.ones((args.eval_batch,), jnp.float32)
            loss_sum += nll_loss(logp, y, wv, reduction="sum")
            correct += ((jnp.argmax(logp, axis=1) == y) * wv).sum()
            return (loss_sum, correct), ()

        def run():
            (ls, c), _ = jax.lax.scan(
                body, (jnp.float32(0.0), jnp.float32(0.0)),
                jnp.arange(args.eval_steps),
            )
            return ls + c
        return run

    def make_full(dropout: bool, gather: str):
        """gather: 'step' (the shipped per-step take), 'none' (fixed
        batch), or 'epoch' (the pre-gathered-epoch candidate)."""
        def body_of(ep_x, ep_y):
            def body(carry, inp):
                p, o, acc, step = carry
                if gather == "step":
                    x = _normalize_dev(jnp.take(images, inp, axis=0),
                                       compute_dtype)
                    y = jnp.take(labels, inp, axis=0)
                elif gather == "epoch":
                    x = _normalize_dev(jax.lax.dynamic_slice_in_dim(
                        ep_x, inp * args.batch, args.batch), compute_dtype)
                    y = jax.lax.dynamic_slice_in_dim(ep_y, inp * args.batch,
                                                     args.batch)
                else:
                    x, y = fixed_x, fixed_y
                dk = jax.random.fold_in(key, step) if dropout else None
                loss, grads = jax.value_and_grad(loss_of)(p, x, y, dk)
                # Single-device mesh: the data-axis pmean of the real step
                # is the identity here; it stays out so this tool needs no
                # mesh.
                p2, o2 = adadelta_update(p, grads, o, lr, 0.9, 1e-6)
                return (p2, o2, acc + loss, step + 1), ()
            return body

        xs = perm if gather == "step" else jnp.arange(args.steps)

        def run():
            if gather == "epoch":
                flat = perm.reshape(-1)
                ep_x = jnp.take(images, flat, axis=0)
                ep_y = jnp.take(labels, flat, axis=0)
            else:
                ep_x = ep_y = None
            (p2, o2, acc, _), _ = jax.lax.scan(
                body_of(ep_x, ep_y),
                (params, opt, jnp.float32(0.0), jnp.int32(0)), xs
            )
            return acc
        return run

    # Decision-value order, not ladder order: through a slow tunnel the
    # per-rung compiles can eat the whole window budget, so the rungs the
    # PERF.md decision rules need most run first and every completed rung
    # is flushed to stderr immediately (a timeout keeps the partials).
    variants = {
        "full": make_full(dropout=True, gather="step"),
        "fwd_bwd": make_fwd_bwd(),
        "full_nogather": make_full(dropout=True, gather="none"),
        "full_pregather": make_full(dropout=True, gather="epoch"),
        "gather_norm": make_gather_norm(),
        "empty_scan": make_empty(),
        "gather_epoch": make_gather_epoch(),
        "full_nodrop": make_full(dropout=False, gather="step"),
        "fwd": make_fwd(),
        "eval": make_eval(),
    }
    # RUNG_NAMES is the module-level export the promotion rule counts
    # against; a rung added here without updating it would be invisible
    # to window_promote's clobber guard.
    assert set(variants) == set(RUNG_NAMES), (
        sorted(variants), sorted(RUNG_NAMES)
    )

    if args.only:
        wanted = [w.strip() for w in args.only.split(",") if w.strip()]
        unknown = [w for w in wanted if w not in variants]
        if unknown:
            print(json.dumps({"metric": "step_attr_us",
                              "error": f"unknown rungs: {unknown}"}))
            return 2
        variants = {k: variants[k] for k in wanted}

    result = {
        "metric": "step_attr_us",
        "backend": backend,
        "device_kind": jax.devices()[0].device_kind,
        "steps": args.steps,
        "batch": args.batch,
        "compute_dtype": "bfloat16" if args.bf16 else "float32",
        "conv_impl": args.conv_impl,
    }

    # The watcher SIGTERMs at its outer timeout; flush whatever completed
    # so the window still yields decision data (the round-4 f32 ladder
    # timed out at 600 s and produced an empty file).
    import signal

    def _flush_partial(signum, frame):
        result.setdefault("partial", True)
        print(json.dumps(result), flush=True)
        sys.exit(124)

    signal.signal(signal.SIGTERM, _flush_partial)
    budget_s = args.budget_s
    t_start = time.perf_counter()

    from pytorch_mnist_ddp_tpu.compile import Program

    for name, fn in variants.items():
        if time.perf_counter() - t_start > budget_s:
            result.setdefault("skipped", []).append(name)
            continue
        # us per ITERATION of that variant's scan ("eval" iterates
        # eval-steps batches; everything else `steps` train steps).
        iters = args.eval_steps if name == "eval" else args.steps
        # Each rung is a Program (compile/program.py): build() is the
        # lower+compile (or persistent-cache load), call the bound
        # executable — the same artifact the trainer and serving
        # dispatch through, so the ladder measures the shipped path.
        rung = Program(name, jax.jit(fn), example_args=())  # jaxlint: disable=JL004 -- one compile per variant IS the measurement (compile_s below)
        try:
            t_c0 = time.perf_counter()
            rung.build()
            jax.block_until_ready(rung.call())  # compile -> first result
            compile_s = time.perf_counter() - t_c0
            best = float("inf")
            for _ in range(args.reps):
                t0 = time.perf_counter()
                jax.block_until_ready(rung.call())
                best = min(best, time.perf_counter() - t0)
            result[name] = round(best / iters * 1e6, 2)
            result.setdefault("compile_s", {})[name] = round(compile_s, 1)
        except Exception as e:  # tunnel drop mid-ladder: keep partials
            result[name] = None
            result.setdefault("errors", {})[name] = repr(e)[:200]
        print(f"[rung] {name}: {result.get(name)} us/iter "
              f"(compile {result.get('compile_s', {}).get(name)}s, "
              f"elapsed {time.perf_counter() - t_start:.0f}s)",
              file=sys.stderr, flush=True)
    if "skipped" in result:
        result["partial"] = True
    # Close the handler race before the final print: a SIGTERM landing
    # mid-print must not let the handler append a second JSON object.
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
