#!/usr/bin/env python3
"""Trainer chaos harness: kill -> resume -> verify bit-exact (PR 9).

The resilient training runtime's acceptance bar (docs/ROBUSTNESS.md) is
not "it resumed" but "the resumed run is byte-identical to a run that
was never killed".  This driver proves it end to end with REAL OS
processes: it runs an uninterrupted baseline, then for each scheduled
kill point launches the trainer with deterministic chaos
(``--chaos kill:step:after=K`` — serving/faults.py's grammar, fired at
an exact step-event count, so there is no timer race), asserts the
process died with the SIGKILL-convention code 137, resumes from the
mid-epoch archive (including the rotated ``.prev`` when the kill landed
inside the checkpoint publish window), and verifies:

- the resumed run's final ``--save-state`` archive equals the
  baseline's ARRAY FOR ARRAY, BIT FOR BIT (params, Adadelta
  accumulators, step counter, BN stats);
- every (epoch, step) -> loss telemetry event of the killed AND resumed
  runs matches the baseline's exactly (the loss-curve half of the bar).

Optional rounds: a real SIGTERM preemption (``--preempt-after-s``:
nondeterministic kill position, same exactness bar — the emergency-save
path), and a NaN-injection round (``--nan-step``) asserting the
LossGuard healed the poisoned step with zero numeric divergence and
exactly one ``train_anomalies_total{kind="nan"}`` in the exposition.

Usage (CI shape — also the local repro):

    python tools/train_chaos.py --workdir /tmp/chaos_train \\
        --synthetic 768 --epochs 2 --checkpoint-every-steps 3 \\
        --kill-steps 4,9,save --nan-step 5

Exit 0 when every scheduled round passed; 1 with per-round FAIL lines
otherwise.  ``save`` in ``--kill-steps`` schedules the mid-save kill
(``kill:ckpt_save:after=1``: die between the rotation and the publish
of the second periodic checkpoint).

**Distributed mode** (``--distributed --nproc 2``, ISSUE 10) drives the
ELASTIC runtime end to end with a real multi-rank gang through the
supervising launcher (``parallel/launch.py --nprocs``):

- an uninterrupted 2-rank baseline;
- a rank-scoped kill round (``--chaos kill:step:rank=1:after=4``): a
  REAL rank dies mid-epoch, the launcher SIGTERMs the survivor with
  bounded grace, gang-restarts from the coordinated mid-epoch archive
  (the children's elastic-resume contract), and the run completes —
  the round FAILS unless ``launch_restarts_total`` ≥ 1, the
  ``rank_death``/``gang_restart`` events fired, and the final params +
  loss curve are byte-identical to the baseline;
- a ``--restart-budget 0`` round: the same kill must escalate to a
  clean non-zero launcher exit with exactly ONE diagnostic line.

    python tools/train_chaos.py --distributed --nproc 2 \\
        --chaos kill:step:rank=1:after=4
"""

from __future__ import annotations

import argparse
import glob
import os
import signal
import struct
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

EXIT_KILLED = 137    # os._exit at the injected kill point (128+SIGKILL)


def _env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Keep any remote-accelerator tunnel out of the subprocesses (same
    # hygiene as tests/conftest.cpu_subprocess_env).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _write_synthetic_idx(root: str, n_train: int, n_test: int) -> None:
    from pytorch_mnist_ddp_tpu.data.mnist import synthetic_mnist

    os.makedirs(root, exist_ok=True)
    xi, yi = synthetic_mnist("train", n=n_train)
    xt, yt = synthetic_mnist("test", n=n_test)
    for name, arr in (
        ("train-images-idx3-ubyte", xi), ("train-labels-idx1-ubyte", yi),
        ("t10k-images-idx3-ubyte", xt), ("t10k-labels-idx1-ubyte", yt),
    ):
        with open(os.path.join(root, name), "wb") as f:
            if arr.ndim == 3:
                f.write(struct.pack(">iiii", 2051, *arr.shape))
            else:
                f.write(struct.pack(">ii", 2049, len(arr)))
            f.write(arr.tobytes())


def _trainer_cmd(args, *, epochs, extra):
    return [
        sys.executable, os.path.join(REPO, "mnist.py"), "--no-accel",
        "--data-root", args.data_root,
        "--epochs", str(epochs),
        "--batch-size", str(args.batch_size),
        "--test-batch-size", str(args.test_batch_size),
        "--seed", str(args.seed),
        "--log-interval", "1000000",
        *extra,
    ]


def _run(cmd, *, cwd=REPO, check_code=None, label=""):
    proc = subprocess.run(
        cmd, cwd=cwd, env=_env(), capture_output=True, text=True
    )
    if check_code is not None and proc.returncode != check_code:
        raise RuntimeError(
            f"{label}: expected exit {check_code}, got {proc.returncode}\n"
            f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-2000:]}"
        )
    return proc


def _step_losses(tel_dir: str) -> dict[tuple[int, int], float]:
    from pytorch_mnist_ddp_tpu.obs.events import read_events

    out: dict[tuple[int, int], float] = {}
    for path in sorted(glob.glob(os.path.join(tel_dir, "*.jsonl"))):
        for e in read_events(path):
            if e.get("event") == "step":
                out[(e["epoch"], e["step"])] = e["loss"]
    return out


def _archive_arrays(path: str) -> dict:
    import numpy as np

    with np.load(path) as z:
        return {k: z[k] for k in z.files if not k.startswith("meta.")}


def _archives_bit_equal(a: str, b: str) -> list[str]:
    """[] when bit-identical; else human-readable mismatch lines."""
    import numpy as np

    za, zb = _archive_arrays(a), _archive_arrays(b)
    problems = []
    if set(za) != set(zb):
        problems.append(
            f"key sets differ: only-in-{a}: {sorted(set(za) - set(zb))}, "
            f"only-in-{b}: {sorted(set(zb) - set(za))}"
        )
    for k in sorted(set(za) & set(zb)):
        va, vb = za[k], zb[k]
        if va.dtype != vb.dtype or va.shape != vb.shape:
            problems.append(f"{k}: {va.dtype}{va.shape} vs {vb.dtype}{vb.shape}")
        elif va.tobytes() != vb.tobytes():
            diff = np.max(np.abs(va.astype(np.float64) - vb.astype(np.float64)))
            problems.append(f"{k}: bytes differ (max |delta| {diff:g})")
    return problems


def _archives_close(a: str, b: str, atol: float) -> list[str]:
    """Same keys/dtypes/shapes and every array within ``atol`` — the
    cross-topology bar (sample-exact continuation, FP-reassociated
    reductions; see the reshard-resume round)."""
    import numpy as np

    za, zb = _archive_arrays(a), _archive_arrays(b)
    problems = []
    if set(za) != set(zb):
        problems.append(
            f"key sets differ: only-in-{a}: {sorted(set(za) - set(zb))}, "
            f"only-in-{b}: {sorted(set(zb) - set(za))}"
        )
    for k in sorted(set(za) & set(zb)):
        va, vb = za[k], zb[k]
        if va.dtype != vb.dtype or va.shape != vb.shape:
            problems.append(f"{k}: {va.dtype}{va.shape} vs {vb.dtype}{vb.shape}")
            continue
        diff = float(
            np.max(np.abs(va.astype(np.float64) - vb.astype(np.float64)))
        ) if va.size else 0.0
        if diff > atol:
            problems.append(f"{k}: max |delta| {diff:g} > atol {atol:g}")
    return problems


def _curve_close_to(sub: dict, base: dict, label: str,
                    atol: float) -> list[str]:
    """Every (epoch, step) of ``sub`` exists in ``base`` within ``atol``
    — the loss-curve-compatibility bar for re-sharded continuations."""
    problems = []
    for key, loss in sorted(sub.items()):
        if key not in base:
            problems.append(f"{label}: step {key} not in baseline curve")
        elif not (abs(loss - base[key]) <= atol
                  or (loss != loss and base[key] != base[key])):
            problems.append(
                f"{label}: loss at {key} = {loss!r} vs baseline "
                f"{base[key]!r} (|delta| > {atol:g})"
            )
    return problems


def _curve_subset_of(sub: dict, base: dict, label: str) -> list[str]:
    problems = []
    for key, loss in sorted(sub.items()):
        if key not in base:
            problems.append(f"{label}: step {key} not in baseline curve")
        elif not (loss == base[key] or (loss != loss and base[key] != base[key])):
            problems.append(
                f"{label}: loss at {key} = {loss!r} != baseline {base[key]!r}"
            )
    return problems


def _epochs_completed(state_path: str) -> int | None:
    """Epochs completed per the archive (or its rotation); None when no
    archive survived (kill before the first cadence) — resume is then a
    fresh start, which reproduces the baseline from the same seed."""
    import numpy as np

    for candidate in (state_path, state_path + ".prev"):
        try:
            with np.load(candidate) as z:
                if "epoch" in z.files:
                    return int(z["epoch"])
        except Exception:
            continue
    return None


def _kill_round(args, name: str, chaos: str, results: list) -> None:
    rd = os.path.join(args.workdir, name)
    os.makedirs(rd, exist_ok=True)
    state = os.path.join(rd, "state.npz")
    final = os.path.join(rd, "final.npz")
    tel_killed = os.path.join(rd, "tel_killed")
    tel_resumed = os.path.join(rd, "tel_resumed")

    _run(
        _trainer_cmd(args, epochs=args.epochs, extra=[
            "--chaos", chaos,
            "--checkpoint-every-steps", str(args.checkpoint_every_steps),
            "--save-state", state,
            "--telemetry-dir", tel_killed,
        ]),
        check_code=EXIT_KILLED, label=f"{name}: killed run",
    )
    if "ckpt_save" in chaos:
        # The mid-save kill must land INSIDE the publish window: no
        # <state>, a complete rotation at <state>.prev — the archive the
        # resume is about to prove loadable.
        if os.path.exists(state) or not os.path.exists(state + ".prev"):
            results.append((name, [
                "mid-save kill did not land in the rotation window "
                f"(state exists={os.path.exists(state)}, "
                f"prev exists={os.path.exists(state + '.prev')})"
            ]))
            return
    done = _epochs_completed(state)
    if done is None:
        # Killed before the first cadence: nothing to resume, rerun from
        # scratch — same seed, same run.
        resume_extra = []
        epochs = args.epochs
    else:
        resume_extra = ["--resume-state", state]
        epochs = args.epochs - done
    _run(
        _trainer_cmd(args, epochs=epochs, extra=[
            *resume_extra,
            "--save-state", final,
            "--telemetry-dir", tel_resumed,
        ]),
        check_code=0, label=f"{name}: resumed run",
    )
    problems = _archives_bit_equal(final, args.baseline_final)
    base_curve = _step_losses(args.baseline_tel)
    problems += _curve_subset_of(
        _step_losses(tel_killed), base_curve, "killed-run curve"
    )
    problems += _curve_subset_of(
        _step_losses(tel_resumed), base_curve, "resumed-run curve"
    )
    results.append((name, problems))


def _preempt_round(args, results: list) -> None:
    name = f"preempt@{args.preempt_after_s:g}s"
    rd = os.path.join(args.workdir, "preempt")
    os.makedirs(rd, exist_ok=True)
    state = os.path.join(rd, "state.npz")
    final = os.path.join(rd, "final.npz")
    tel_resumed = os.path.join(rd, "tel_resumed")
    proc = subprocess.Popen(
        _trainer_cmd(args, epochs=args.epochs, extra=[
            "--checkpoint-every-steps", str(args.checkpoint_every_steps),
            "--save-state", state,
        ]),
        cwd=REPO, env=_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    time.sleep(args.preempt_after_s)
    proc.send_signal(signal.SIGTERM)
    code = proc.wait(timeout=120)
    if code == 0:
        print(f"  note: {name}: run finished before the SIGTERM landed; "
              "verifying its own final archive instead")
        results.append((name, _archives_bit_equal(state, args.baseline_final)))
        return
    if code != 128 + signal.SIGTERM:
        results.append((name, [
            f"expected exit {128 + signal.SIGTERM} (emergency save + clean "
            f"exit) or 0, got {code}"
        ]))
        return
    done = _epochs_completed(state)
    if done is None:
        results.append((name, ["SIGTERM landed but no archive was written"]))
        return
    _run(
        _trainer_cmd(args, epochs=args.epochs - done, extra=[
            "--resume-state", state,
            "--save-state", final,
            "--telemetry-dir", tel_resumed,
        ]),
        check_code=0, label=f"{name}: resumed run",
    )
    problems = _archives_bit_equal(final, args.baseline_final)
    problems += _curve_subset_of(
        _step_losses(tel_resumed), _step_losses(args.baseline_tel),
        "resumed-run curve",
    )
    results.append((name, problems))


def _nan_round(args, results: list) -> None:
    name = f"nan@step{args.nan_step}"
    rd = os.path.join(args.workdir, "nan")
    os.makedirs(rd, exist_ok=True)
    final = os.path.join(rd, "final.npz")
    tel = os.path.join(rd, "tel")
    _run(
        _trainer_cmd(args, epochs=args.epochs, extra=[
            "--chaos", f"nan:step:after={args.nan_step}",
            "--loss-guard",
            "--save-state", final,
            "--telemetry-dir", tel,
        ]),
        check_code=0, label=f"{name}: guarded run",
    )
    problems = _archives_bit_equal(final, args.baseline_final)
    prom_path = os.path.join(tel, "metrics.prom")
    try:
        prom = open(prom_path).read()
    except OSError:
        prom = ""
    if 'train_anomalies_total{kind="nan"} 1' not in prom:
        problems.append(
            f"{prom_path}: expected exactly one "
            'train_anomalies_total{kind="nan"}; got: '
            + repr([l for l in prom.splitlines() if "anomal" in l])
        )
    results.append((name, problems))


# ---------------------------------------------------------------------------
# Distributed mode (ISSUE 10): real multi-rank gang through the
# supervising launcher.


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launcher_cmd(args, *, port, launch_extra, trainer_extra):
    """One supervised 2-rank world: N rank processes x 1 CPU device."""
    return [
        sys.executable, "-m", "pytorch_mnist_ddp_tpu.parallel.launch",
        "--nprocs", str(args.nproc), "--nproc_per_node", "1",
        "--backend", "cpu", "--master_port", str(port),
        "--rdzv-timeout-s", "120",
        *launch_extra,
        os.path.join(REPO, "mnist_ddp.py"), "--no-accel",
        "--data-root", args.data_root,
        "--epochs", str(args.epochs),
        "--batch-size", str(args.batch_size),
        "--test-batch-size", str(args.test_batch_size),
        "--seed", str(args.seed),
        "--log-interval", "1000000",
        *trainer_extra,
    ]


def _read_events(tel_dir: str, name: str) -> list[dict]:
    from pytorch_mnist_ddp_tpu.obs.events import read_events

    out = []
    for path in sorted(glob.glob(os.path.join(tel_dir, "*.jsonl"))):
        out.extend(e for e in read_events(path) if e.get("event") == name)
    return out


def _distributed_main(args) -> int:
    """Baseline -> rank-kill gang-restart -> budget-0 escalation."""
    print(f"train_chaos[distributed]: {args.nproc}-rank gang, "
          f"workdir {args.workdir}, chaos {args.chaos!r}")
    results: list[tuple[str, list[str]]] = []

    base_dir = os.path.join(args.workdir, "dist_baseline")
    base_tel = os.path.join(base_dir, "tel")
    baseline_final = os.path.join(base_dir, "final.npz")
    os.makedirs(base_dir, exist_ok=True)
    t0 = time.perf_counter()
    _run(
        _launcher_cmd(args, port=_free_port(), launch_extra=[], trainer_extra=[
            "--save-state", baseline_final,
            "--telemetry-dir", base_tel,
        ]),
        check_code=0, label="distributed baseline",
    )
    base_curve = _step_losses(base_tel)
    print(f"  baseline: {args.epochs} epoch(s) x {args.nproc} ranks, "
          f"{len(base_curve)} steps ({time.perf_counter() - t0:.1f} s)")

    # Round 1: rank-scoped kill -> supervisor gang-restart -> resume ->
    # byte-identical finish.
    name = f"gang-kill[{args.chaos}]"
    rd = os.path.join(args.workdir, "dist_kill")
    tel = os.path.join(rd, "tel")
    state = os.path.join(rd, "state.npz")
    os.makedirs(rd, exist_ok=True)
    _run(
        _launcher_cmd(
            args, port=_free_port(),
            launch_extra=[
                "--restart-budget", "2", "--grace-s", "10",
                "--backoff-base-s", "0.1", "--telemetry-dir", tel,
            ],
            trainer_extra=[
                "--chaos", args.chaos,
                "--preempt-grace-s", "5",
                "--checkpoint-every-steps",
                str(args.checkpoint_every_steps),
                "--save-state", state,
                "--telemetry-dir", tel,
            ],
        ),
        check_code=0, label=f"{name}: supervised run",
    )
    problems = _archives_bit_equal(state, baseline_final)
    gang_curve = _step_losses(tel)
    problems += _curve_subset_of(gang_curve, base_curve, "gang curve")
    if base_curve and max(base_curve) not in gang_curve:
        problems.append(
            f"gang curve never reached the baseline's final step "
            f"{max(base_curve)} (resume did not finish the run)"
        )
    deaths = _read_events(tel, "rank_death")
    restarts = _read_events(tel, "gang_restart")
    if not deaths:
        problems.append("no rank_death event: the kill never fired "
                        "(vacuous green)")
    if not restarts:
        problems.append("no gang_restart event: the supervisor never "
                        "restarted the world")
    prom_path = os.path.join(tel, "launcher.prom")
    try:
        prom = open(prom_path).read()
    except OSError:
        prom = ""
    if not any(
        line.startswith("launch_restarts_total ")
        and float(line.split()[-1]) >= 1
        for line in prom.splitlines()
    ):
        problems.append(f"{prom_path}: launch_restarts_total >= 1 missing")
    results.append((name, problems))

    # Round 2: the same kill with --restart-budget 0 must escalate to a
    # clean non-zero exit with exactly ONE diagnostic.
    name0 = "gang-budget0"
    rd0 = os.path.join(args.workdir, "dist_budget0")
    tel0 = os.path.join(rd0, "tel")
    os.makedirs(rd0, exist_ok=True)
    proc0 = _run(
        _launcher_cmd(
            args, port=_free_port(),
            launch_extra=[
                "--restart-budget", "0", "--grace-s", "10",
                "--telemetry-dir", tel0,
            ],
            trainer_extra=[
                "--chaos", args.chaos,
                "--preempt-grace-s", "5",
                "--checkpoint-every-steps",
                str(args.checkpoint_every_steps),
                "--save-state", os.path.join(rd0, "state.npz"),
            ],
        ),
    )
    problems0 = []
    if proc0.returncode == 0:
        problems0.append("budget-0 launcher exited 0: the kill never "
                         "escalated")
    diags = [
        line for line in proc0.stderr.splitlines()
        if line.startswith("launch: gang failed")
    ]
    if len(diags) != 1:
        problems0.append(
            f"expected exactly one 'launch: gang failed' diagnostic, got "
            f"{len(diags)}: {diags!r}"
        )
    results.append((name0, problems0))

    # Round 3: cross-topology resume — the archive the exhausted gang
    # left behind (coordinated at world size N by N rank processes)
    # resumes in ONE process driving N local devices.  The sampler
    # contract makes every remaining batch the SAME global sample set,
    # but the process striping re-partitions it across devices, so
    # reductions re-associate: the continuation is SAMPLE-exact and
    # loss-curve-compatible (tolerance), not bit-exact — only a
    # same-topology restart (round 1) can be byte-identical.
    name1 = "reshard-resume"
    state0 = os.path.join(rd0, "state.npz")
    problems1: list[str] = []
    if not (os.path.exists(state0) or os.path.exists(state0 + ".prev")):
        problems1.append(
            "the exhausted gang left no coordinated archive to resume"
        )
    else:
        _run(
            [
                sys.executable, "-m",
                "pytorch_mnist_ddp_tpu.parallel.launch",
                "--nproc_per_node", str(args.nproc), "--backend", "cpu",
                os.path.join(REPO, "mnist_ddp.py"), "--no-accel",
                "--data-root", args.data_root,
                "--epochs", str(args.epochs),
                "--batch-size", str(args.batch_size),
                "--test-batch-size", str(args.test_batch_size),
                "--seed", str(args.seed),
                "--log-interval", "1000000",
                "--elastic",  # resume own archive, epochs-as-total
                "--save-state", state0,
                "--telemetry-dir", tel0,
            ],
            check_code=0, label=f"{name1}: single-process resume",
        )
        problems1 += _archives_close(state0, baseline_final, atol=0.15)
        reshard_curve = _step_losses(tel0)
        problems1 += _curve_close_to(
            reshard_curve, base_curve, "reshard curve", atol=0.35
        )
        if base_curve and max(base_curve) not in reshard_curve:
            problems1.append(
                "reshard curve never reached the baseline's final step"
            )
    results.append((name1, problems1))

    failed = False
    for rname, rproblems in results:
        if rproblems:
            failed = True
            print(f"FAIL {rname}:")
            for line in rproblems:
                print(f"    {line}")
        else:
            print(f"PASS {rname}")
    return 1 if failed else 0


def main() -> int:
    p = argparse.ArgumentParser(
        description="trainer chaos harness: kill -> resume -> verify "
        "bit-exact params + loss curve"
    )
    p.add_argument("--workdir", default=None,
                   help="scratch directory (default: a fresh temp dir)")
    p.add_argument("--data-root", default=None,
                   help="MNIST IDX directory (default: generate --synthetic)")
    p.add_argument("--synthetic", type=int, default=768, metavar="N",
                   help="generate an N-sample synthetic train set "
                        "(N//3 test) when no --data-root (default: 768)")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--test-batch-size", type=int, default=256)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--checkpoint-every-steps", type=int, default=3)
    p.add_argument("--kill-steps", default="4,9,save",
                   help="comma list of deterministic kill points: step-event "
                        "counts and/or 'save' (mid-checkpoint-publish kill); "
                        "default: 4,9,save")
    p.add_argument("--preempt-after-s", type=float, default=0.0, metavar="T",
                   help="also run a real-SIGTERM preemption round T seconds "
                        "into the run (0 = skip; timing-dependent by design)")
    p.add_argument("--nan-step", type=int, default=5, metavar="K",
                   help="NaN-injection round: poison step K under "
                        "--loss-guard and require a bit-exact heal "
                        "(-1 = skip; default: 5)")
    p.add_argument("--distributed", action="store_true", default=False,
                   help="elastic-runtime mode (ISSUE 10): drive a real "
                        "--nproc-rank gang through the supervising "
                        "launcher, kill one rank mid-epoch (--chaos), and "
                        "require gang-restart + byte-identical finish")
    p.add_argument("--nproc", type=int, default=2, metavar="N",
                   help="rank processes in the distributed gang "
                        "(default: 2)")
    p.add_argument("--chaos", default="kill:step:rank=1:after=4",
                   metavar="SPEC",
                   help="distributed-round chaos clause (rank-scoped "
                        "trainer grammar; default: kill rank 1 before its "
                        "5th step)")
    args = p.parse_args()

    if args.workdir is None:
        import tempfile

        args.workdir = tempfile.mkdtemp(prefix="train_chaos_")
    os.makedirs(args.workdir, exist_ok=True)
    if args.data_root is None:
        args.data_root = os.path.join(args.workdir, "data")
        _write_synthetic_idx(args.data_root, args.synthetic,
                             max(args.synthetic // 3, args.test_batch_size))
    if args.distributed:
        return _distributed_main(args)
    print(f"train_chaos: workdir {args.workdir}, data {args.data_root}")

    base_dir = os.path.join(args.workdir, "baseline")
    os.makedirs(base_dir, exist_ok=True)
    args.baseline_final = os.path.join(base_dir, "final.npz")
    args.baseline_tel = os.path.join(base_dir, "tel")
    t0 = time.perf_counter()
    _run(
        _trainer_cmd(args, epochs=args.epochs, extra=[
            "--save-state", args.baseline_final,
            "--telemetry-dir", args.baseline_tel,
        ]),
        check_code=0, label="baseline run",
    )
    n_steps = len(_step_losses(args.baseline_tel))
    print(f"  baseline: {args.epochs} epoch(s), {n_steps} steps "
          f"({time.perf_counter() - t0:.1f} s)")

    results: list[tuple[str, list[str]]] = []
    for spec in [s.strip() for s in args.kill_steps.split(",") if s.strip()]:
        if spec == "save":
            _kill_round(args, "kill@ckpt_save", "kill:ckpt_save:after=1",
                        results)
        else:
            k = int(spec)
            if not 0 <= k < n_steps:
                print(f"  note: kill step {k} outside the run's "
                      f"{n_steps} steps; it would never fire — skipping")
                continue
            _kill_round(args, f"kill@step{k}", f"kill:step:after={k}", results)
    if args.preempt_after_s > 0:
        _preempt_round(args, results)
    if args.nan_step >= 0:
        _nan_round(args, results)

    failed = False
    for name, problems in results:
        if problems:
            failed = True
            print(f"FAIL {name}:")
            for line in problems:
                print(f"    {line}")
        else:
            print(f"PASS {name}: resumed run bit-identical to baseline")
    if not results:
        print("train_chaos: nothing ran (empty schedule?)")
        return 1
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
