"""Replica-shape sweep + the sharded structural pin (ISSUE 20).

Two halves, one committed artifact (``BENCH_sharded.json``):

**Structural pin (fake devices).**  The CI box is a host-bound CPU
container, so the tensor-parallel win is pinned the way every serving
win in this repo is pinned (tests/test_scaleout.py, the fleet fake
rung): fake engines whose launch returns instantly and whose "compute"
completes after a service delay — real accelerator semantics.  A
k-device TP replica's full-batch service time is ``service_ms / k``
(column-parallel layers split the matmuls k ways; the psum is modeled
inside the same delay), a 1-device replica's is ``service_ms``.  An
oversized request stream (every request larger than the bucket, so the
batcher splits it into full serial batches) is driven through identical
batcher/router plumbing; the pin asserts the 4-device TP replica beats
the 1-device serial dispatch by the acceptance margin (>25% wall) —
structurally, not by host-noise luck.

**Real-engine sweep (virtual devices).**  Every replica-shape plan
(pure DP, pure TP, mixed TP+DP, EP pair) is then built as a REAL
``EnginePool`` over the 8-virtual-device CPU mesh: warmed, parity-gated,
and driven through the cost router.  CPU wall times for sharded rungs
carry no speedup claim (the ``host_bound_caveat`` — a virtual-device
mesh shares the same cores), but the *correctness* invariants are
asserted per rung: the parity gate passed, and the drive added ZERO
post-warmup compiles.

Exits non-zero if the structural pin misses the margin, any parity gate
fails, or any real rung compiles after warmup.

Usage:
    python tools/sharded_bench.py [--report BENCH_sharded.json]
        [--requests 48] [--max-request 24] [--service-ms 40]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

NUM_CLASSES = 10
BUCKET = 8  # the fake rungs' single bucket; oversized requests split


# ---------------------------------------------------------------------------
# Fake half: device-faithful async-completion engines


class _LazyLogits:
    """Launch returns instantly; __array__ blocks until the modeled
    device would have finished — the test_scaleout.py fake."""

    def __init__(self, rows: np.ndarray, delay_s: float):
        self._rows = np.array(rows, copy=True)
        self._t_ready = time.perf_counter() + delay_s

    def __array__(self, dtype=None, copy=None):
        wait = self._t_ready - time.perf_counter()
        if wait > 0:
            time.sleep(wait)
        out = np.zeros((len(self._rows), NUM_CLASSES), np.float32)
        return out if dtype is None else out.astype(dtype)


class FakeShardedEngine:
    """A replica of ``devices`` fake devices: full-batch service time is
    ``service_s / devices`` (TP splits the matmuls; DP has k=1)."""

    def __init__(self, devices: int, service_s: float):
        self.buckets = (BUCKET,)
        self.metrics = None
        self.devices = devices
        self.service_s = service_s / devices
        self.dispatches: list[int] = []

    def launch(self, staged, n):
        self.dispatches.append(n)
        return _LazyLogits(staged, self.service_s)


def _drive_fake_rung(shapes: list[int], service_s: float,
                     requests: int, max_request: int) -> dict:
    """``shapes`` = fake-device count per replica; returns the rung row."""
    from pytorch_mnist_ddp_tpu.serving import (
        MicroBatcher, Replica, Router, ServingMetrics,
    )

    metrics = ServingMetrics()
    replicas, engines = [], []
    for i, k in enumerate(shapes):
        engine = FakeShardedEngine(k, service_s)
        batcher = MicroBatcher(
            engine, metrics=metrics, replica=f"r{i}",
            linger_ms=0.0, adaptive_linger=False, max_inflight=1,
            timeout_ms=300_000.0, queue_depth=512,
        )
        replica = Replica(f"r{i}", batcher, engine=engine)
        batcher.on_complete = replica.observe_latency
        batcher.start()
        replicas.append(replica)
        engines.append(engine)
    router = Router(replicas, policy="cost", metrics=metrics)
    # Every request is OVERSIZED (3x the bucket): it pays three full
    # serial batches on a 1-device replica, three k-times-faster batches
    # on a TP replica, and spreads chunks across a multi-replica pool.
    # The split happens client-side in bucket-sized chunks because a
    # single replica's admission honestly caps at one maximal batch.
    chunks_per_req = max_request // BUCKET + (1 if max_request % BUCKET else 0)
    x = np.zeros((BUCKET, 28, 28, 1), np.float32)
    reqs = [
        router.submit(x)
        for _ in range(requests)
        for _chunk in range(chunks_per_req)
    ]
    t0 = time.perf_counter()
    for r in reqs:
        out = r.result(grace_s=300.0)
        assert out.shape == (BUCKET, NUM_CLASSES)
    wall = time.perf_counter() - t0
    router.stop()
    dispatched = sum(len(e.dispatches) for e in engines)
    return {
        "replica_shapes": [f"{'tp' if k > 1 else 'dp'}{k}" for k in shapes],
        "fake_devices": sum(shapes),
        "batches_dispatched": dispatched,
        "wall_s": wall,
    }


def run_structural_pin(args) -> dict:
    service_s = args.service_ms / 1e3
    rungs = {
        # One 1-device replica: the serial-dispatch baseline every
        # oversized request pays in full.
        "dp1": _drive_fake_rung([1], service_s, args.requests,
                                args.max_request),
        # One 4-device TP replica: same serial batch stream, each batch
        # 4x faster — the giant-model shape (the model does not FIT on
        # one device; DP is not an option for it).
        "tp4": _drive_fake_rung([4], service_s, args.requests,
                                args.max_request),
        # Four 1-device DP replicas: the classic scale-out answer when
        # the model does fit.
        "dp4": _drive_fake_rung([1, 1, 1, 1], service_s, args.requests,
                                args.max_request),
        # Mixed pool over 8 fake devices: tp4 + 4x dp behind the cost
        # router's per-shape-class EWMAs.
        "tp4_dp4": _drive_fake_rung([4, 1, 1, 1, 1], service_s,
                                    args.requests, args.max_request),
    }
    base = rungs["dp1"]["wall_s"]
    for row in rungs.values():
        row["speedup_vs_dp1"] = base / row["wall_s"]
    win = 1.0 - rungs["tp4"]["wall_s"] / base
    pin = {
        "service_ms": args.service_ms,
        "requests": args.requests,
        "max_request": args.max_request,
        "bucket": BUCKET,
        "rungs": rungs,
        "tp4_win_vs_dp1": win,
        "min_win": 0.25,
        "passed": win > 0.25,
    }
    print(f"structural pin: tp4 wall {rungs['tp4']['wall_s']:.3f}s vs "
          f"dp1 {base:.3f}s -> win {win:.1%} (need >25%)"
          f"{' PASS' if pin['passed'] else ' FAIL'}")
    return pin


# ---------------------------------------------------------------------------
# Real half: every shape plan as a live pool on the virtual-device mesh


REAL_PLANS = [
    ("dp,dp,dp,dp", 4),
    ("tp4", 1),
    ("tp4,dp,dp,dp,dp", 5),
    ("ep2,ep2", 2),
    ("pp2,pp2", 2),
]


def run_real_sweep(args) -> list[dict]:
    from pytorch_mnist_ddp_tpu.serving import EnginePool, ServingMetrics

    rows = []
    rng = np.random.RandomState(20260807)
    for shapes, n_replicas in REAL_PLANS:
        metrics = ServingMetrics()
        pool = EnginePool.from_seed(
            replicas=n_replicas, replica_shapes=shapes, buckets=(8, 16),
            metrics=metrics,
        )
        pool.warmup(parallel=True)  # parity-gates every sharded replica
        parity = {
            e.shard_kind: e.parity_report.get("f32", {})
            for e in pool.engines if e.shard_kind != "dp"
        }
        router = pool.start(router_policy="cost", linger_ms=1.0,
                            timeout_ms=120_000.0, queue_depth=512)
        compiles_before = pool.compile_count()
        # Oversized where the pool has the capacity to shard it (the
        # router splits across replicas); the top bucket otherwise.
        n = min(args.max_request, n_replicas * 16)
        x = rng.rand(n, 28, 28, 1).astype(np.float32)
        t0 = time.perf_counter()
        reqs = [router.submit(x) for _ in range(args.requests)]
        for r in reqs:
            assert r.result(grace_s=60.0).shape == (n, 10)
        wall = time.perf_counter() - t0
        added = pool.compile_count() - compiles_before
        pool.stop()
        row = {
            "replica_shapes": shapes,
            "replicas": n_replicas,
            "devices": sum(
                len(list(e.mesh.devices.flat)) for e in pool.engines
            ),
            "wall_s": wall,
            "warmup_compiles": compiles_before,
            "additional_compiles": added,
            "parity": {
                kind: {
                    "max_abs_logit_diff": p.get("max_abs_logit_diff"),
                    "tolerance": p.get("tolerance"),
                    "passed": p.get("passed"),
                }
                for kind, p in parity.items()
            },
            "passed": added == 0 and all(
                p.get("passed") for p in parity.values()
            ) if parity else added == 0,
        }
        rows.append(row)
        print(f"real rung {shapes!r}: wall {wall:.2f}s, "
              f"warmup compiles {compiles_before}, added {added}"
              f"{' PASS' if row['passed'] else ' FAIL'}")
    return rows


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--report", default="BENCH_sharded.json")
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--max-request", type=int, default=24)
    ap.add_argument("--service-ms", type=float, default=40.0)
    args = ap.parse_args()

    pin = run_structural_pin(args)
    sweep = run_real_sweep(args)
    report = {
        "mode": "sharded-sweep",
        "host_bound_caveat": (
            "real-rung wall times share one CPU across all virtual "
            "devices; the speedup claim lives in the fake-device "
            "structural pin"
        ),
        "structural_pin": pin,
        "real_sweep": sweep,
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {args.report}")
    ok = pin["passed"] and all(r["passed"] for r in sweep)
    print("SHARDED BENCH:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
