"""Artifact promotion rules for the tunnel-window watcher.

Tunnel throughput is bimodal (round 3: 9.3 s vs 61.8 s for the same
warm program minutes apart; round 5: 3.8x run_s swing on the warm
headline), so recorded rows are never latest-wins:

- ``value``: copy src over dst only if src's ``"value"`` beats (is
  lower than) dst's — the rule for every bench row the watcher records
  (`bench_r5_warm.json`, variant rows, ViT rows).  The ``.err`` sidecar
  travels with its json.  A src without a numeric value (a structured-
  failure row, or unparseable bytes) is NEVER promoted, even onto an
  absent dst — promoted artifacts hold measurements only; failure
  breadcrumbs live in the per-run `*_run.json`/`.err` files and the
  watcher log.  (Deliberate change from the pre-extraction heredoc,
  which copied a failure row onto an absent dst.)
- ``rungs``: copy src over dst only if src carries at least as many
  measured ladder rungs — the rule for the unsuffixed step-attribution
  baseline `tools/perf_report.py` reads, AND for the batch-scaling
  `_b1000` artifact (so both sides of perf_report's batch-scaling
  ratio are cross-window minima, per docs/PERF.md rule 2).  Rungs are
  counted against the KNOWN rung-name set `step_attr_bench.RUNG_NAMES`
  (numeric values only — a failed rung records None), so a future
  top-level float metadata key (elapsed_s, budget_s, ...) can never
  inflate a truncated partial's count and let it clobber a more
  complete committed baseline, while the FIRST partial still lands.

Usage: python tools/window_promote.py {value|rungs} SRC.json DST.json
Exit 0 either way (promotion declined is not an error); 2 on bad usage.
"""

from __future__ import annotations

import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
# The ladder tool's own rung-name export (stdlib-only import): the one
# source of truth for what counts as a measured rung.
from step_attr_bench import RUNG_NAMES


def _load(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def promote_value(src: str, dst: str) -> str:
    """Min-by-``value`` promotion; returns a human-readable outcome."""
    src_row = _load(src)
    new = src_row.get("value") if isinstance(src_row, dict) else None
    if not isinstance(new, (int, float)):
        return f"kept incumbent (new run has no value: {src})"
    dst_row = _load(dst)
    old = dst_row.get("value") if isinstance(dst_row, dict) else None
    if isinstance(old, (int, float)) and old <= new:
        return f"kept {old} (new run {new} is slower)"
    shutil.copy(src, dst)
    err = src[: -len(".json")] + ".err" if src.endswith(".json") else None
    if err and os.path.exists(err) and dst.endswith(".json"):
        shutil.copy(err, dst[: -len(".json")] + ".err")
    return f"promoted {new} (previous {old})"


def count_rungs(row: dict | None) -> int:
    """Measured-rung count of a ladder artifact: keys from the known
    rung-name set (``step_attr_bench.RUNG_NAMES``) holding a numeric
    measurement.  A failed rung records None (not counted); top-level
    numeric METADATA keys are not rungs and must never let a truncated
    partial outrank a more complete committed baseline."""
    if not isinstance(row, dict):
        return -1
    return sum(
        1 for k, v in row.items()
        if k in RUNG_NAMES
        and isinstance(v, (int, float)) and not isinstance(v, bool)
    )


def promote_rungs(src: str, dst: str) -> str:
    """Most-measured-rungs promotion; returns a human-readable outcome.

    Ties on rung count break toward the lower ``full`` rung: with the
    short post-window pause the playbook re-runs in later (possibly
    slow-mode) passes, and a complete slow-mode ladder must not clobber
    a complete fast-mode one — the minimum over windows is the one
    robust cross-window statistic (docs/PERF.md)."""
    src_row, dst_row = _load(src), _load(dst)
    n_src, n_dst = count_rungs(src_row), count_rungs(dst_row)
    if n_src <= 0 or n_src < n_dst:
        return f"stepattr kept incumbent ({n_dst} rungs vs new {n_src})"
    if n_src == n_dst:
        old = dst_row.get("full") if isinstance(dst_row, dict) else None
        new = src_row.get("full") if isinstance(src_row, dict) else None
        if (isinstance(old, (int, float)) and
                not (isinstance(new, (int, float)) and new < old)):
            return (f"stepattr kept incumbent (tie at {n_dst} rungs, "
                    f"full {old} <= {new})")
    shutil.copy(src, dst)
    return f"stepattr promoted ({n_src} rungs over {n_dst})"


def main(argv: list[str]) -> int:
    if len(argv) != 4 or argv[1] not in ("value", "rungs"):
        print("usage: python tools/window_promote.py {value|rungs} "
              "SRC.json DST.json", file=sys.stderr)
        return 2
    fn = promote_value if argv[1] == "value" else promote_rungs
    print(fn(argv[2], argv[3]))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
