"""Attempt to fetch the real MNIST IDX files; log the outcome durably.

The reference trains on the actual IDX files (reference mnist_ddp.py:153-160)
and its README speed table is real-MNIST wall clock.  This host is normally
air-gapped, so `data/` stays empty and every recorded run says
``dataset: "synthetic"`` — but network conditions MAY differ while the
accelerator tunnel is up (round-3 verdict, next-round item 3).  The watcher
therefore runs this tool at the top of every tunnel window; each attempt's
outcome is appended to ``data/idx_attempts.log`` (committed), so either the
files eventually land (and bench.py records an ``dataset: "idx"`` row) or
the log proves the attempts were made.

Usage: python tools/fetch_mnist.py [--root DIR]
Exit 0 if all four files are present afterwards, 1 otherwise.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytorch_mnist_ddp_tpu.data.mnist import (  # noqa: E402
    _FILES,
    _MIRRORS,
    _read_maybe_gz,
    _try_download,
    verify_idx_digest,
)

LOG_PATH = os.path.join(REPO, "data", "idx_attempts.log")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--root", default=os.environ.get(
        "MNIST_DATA_DIR", os.path.join(REPO, "data")))
    args = p.parse_args()

    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    # Log the attempt BEFORE downloading: hanging connections can run to
    # ~160 s total and an outer timeout may SIGTERM this process — the
    # begin line proves the attempt even then (round-4 review finding).
    os.makedirs(os.path.dirname(LOG_PATH), exist_ok=True)
    with open(LOG_PATH, "a") as f:
        f.write(f"{stamp} root={args.root} begin\n")
    present, fetched, failed, verified = [], [], [], []
    for key, filename in _FILES.items():
        path = os.path.join(args.root, filename)
        raw = _read_maybe_gz(path)
        # Golden-digest check (data/mnist.py): the log then proves not just
        # that bytes landed but that they are the canonical files.  A
        # present-but-non-canonical file (corrupt/truncated earlier fetch)
        # is retried: the mirror may hold the real bytes one download away
        # (_try_download only overwrites on a successful decompress).
        ok_digest = raw is not None and verify_idx_digest(filename, raw)
        if raw is not None and not ok_digest:
            fresh = _try_download(args.root, filename)
            if fresh is not None:
                fetched.append(filename)
                ok_digest = verify_idx_digest(filename, fresh)
            else:
                present.append(filename)
        elif raw is not None:
            present.append(filename)
        else:
            raw = _try_download(args.root, filename)
            if raw is not None:
                fetched.append(filename)
                ok_digest = verify_idx_digest(filename, raw)
            else:
                failed.append(filename)
        if ok_digest:
            verified.append(filename)

    ok = not failed
    line = (
        f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} "
        f"root={args.root} present={len(present)} "
        f"fetched={len(fetched)} failed={len(failed)} "
        f"verified={len(verified)}/4 "
        f"mirrors={','.join(_MIRRORS)} "
        f"outcome={'complete' if ok else 'failed:' + ','.join(failed)}"
    )
    with open(LOG_PATH, "a") as f:
        f.write(line + "\n")
    print(line)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
