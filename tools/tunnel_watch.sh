#!/bin/bash
# Round-long accelerator-tunnel watcher (round-2 verdict, next-round item 1).
#
# The TPU tunnel on this host is up only in short windows (round 2: one
# 8-minute window in ~20 hours).  This script polls cheaply and, the moment
# the chip answers, runs the DOUBLE-BENCH protocol:
#   run 1  — headline config, re-warms the persistent XLA cache (any commit
#            that changed the fused program's HLO invalidated it)
#   run 2  — headline config again, records the WARM steady-state number
#            (updates bench_last_good.json via bench.py's snapshot logic)
#   run 3+ — --bf16 and --syncbn variant rows (verdict item 6), recorded to
#            their own files; never touch the headline snapshot
# After a successful window it keeps polling (a later window re-warms the
# cache so the driver's round-end `python bench.py` hits it warm).
#
# Usage: nohup bash tools/tunnel_watch.sh >/tmp/tunnel_watch_r3.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
REPO="$PWD"
OUT="$REPO"
# Windows can be VERY short (observed 2026-07-31: ~80 s, vs round 2's 8 min).
# Poll fast — the probe itself costs up to 95 s when the tunnel is down, so
# the effective cycle is ~2.5 min — and bound every bench run so a tunnel
# drop mid-run cannot wedge the watcher past the next window.
POLL_S=${POLL_S:-60}
POST_WINDOW_SLEEP_S=${POST_WINDOW_SLEEP_S:-900}
BENCH_TIMEOUT_S=${BENCH_TIMEOUT_S:-240}

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

probe() {
    timeout 95 python -c "import jax; d=jax.devices(); import sys; sys.exit(0 if d[0].platform != 'cpu' else 1)" \
        >/dev/null 2>&1
}

run_bench() { # $1 = tag, rest = extra bench.py args
    local tag="$1"; shift
    echo "[$(stamp)] bench $tag start"
    # Two layers of bounding: bench.py's own watchdog (structured failure
    # JSON) and an outer `timeout` in case the watchdog thread itself is
    # starved by a dead tunnel.  The watchdog timer starts after the backend
    # probe (itself up to ~90 s), so the outer bound must cover probe +
    # watchdog + margin or it would SIGTERM bench.py before the watchdog
    # can write the structured failure record.
    timeout $((BENCH_TIMEOUT_S + 180)) \
        python "$REPO/bench.py" --probe-attempts 1 --run-timeout "$BENCH_TIMEOUT_S" "$@" \
        >"$OUT/bench_r3_${tag}.json" 2>"$OUT/bench_r3_${tag}.err"
    local rc=$?
    echo "[$(stamp)] bench $tag rc=$rc: $(cat "$OUT/bench_r3_${tag}.json" 2>/dev/null | head -c 400)"
    return $rc
}

is_warm() { # $1 = tag; true if that run's JSON recorded a warm cache
    grep -q '"cache": "warm"' "$OUT/bench_r3_$1.json" 2>/dev/null
}

promote() { # $1 = src tag, $2 = dst tag; copy ONLY if src beats dst.
    # The tunnel's throughput is bimodal (observed 9.3 s and 61.8 s for
    # the same warm program minutes apart); latest-wins writes let a
    # slow-mode run clobber a best record, so every recorded row is
    # min-by-value.  The .err sidecar travels with its json.
    python - "$OUT/bench_r3_$1" "$OUT/bench_r3_$2" <<'EOF'
import json, os, shutil, sys
src, dst = sys.argv[1], sys.argv[2]
new = json.load(open(src + ".json"))["value"]
try:
    old = json.load(open(dst + ".json"))["value"]
except Exception:
    old = None
if old is None or (new is not None and new < old):
    shutil.copy(src + ".json", dst + ".json")
    if os.path.exists(src + ".err"):
        shutil.copy(src + ".err", dst + ".err")
    print(f"promoted {new} (previous {old})")
else:
    print(f"kept {old} (new run {new} is slower)")
EOF
}

echo "[$(stamp)] watcher up, polling every ${POLL_S}s"
while true; do
    if probe; then
        echo "[$(stamp)] TUNNEL UP — double-bench"
        run_bench warmup || { sleep "$POLL_S"; continue; }
        # The persistent XLA cache survives between windows: once ANY run has
        # compiled the headline program, the next window's FIRST run is
        # already warm.  Promote it and spend the remaining window on the
        # variant rows instead of burning ~40 s re-measuring.
        if is_warm warmup; then
            echo "[$(stamp)] warmup ran warm — $(promote warmup warm)"
        else
            # Cold first run: bench again (now warm) to a SCRATCH tag and
            # min-promote — a direct write here could let a slow-mode run
            # clobber the standing warm record.
            run_bench warm_run || { sleep "$POLL_S"; continue; }
            if is_warm warm_run; then
                echo "[$(stamp)] $(promote warm_run warm)"
            fi
        fi
        # Variant rows only after the headline record is safe; each row is
        # min-by-value too (scratch tag then promote).
        run_bench bf16_run --bf16 && echo "[$(stamp)] bf16: $(promote bf16_run bf16)"
        run_bench syncbn_run --syncbn && echo "[$(stamp)] syncbn: $(promote syncbn_run syncbn)"
        # Pallas-kernel decision data (verdict item 7): full-run row with
        # the flat-state kernel, plus the optimizer-only micro-benchmark.
        run_bench pallas_run --pallas-opt && echo "[$(stamp)] pallas: $(promote pallas_run pallas)"
        # ZeRO-1 row (parallel/zero.py): per-batch path (the sharded-state
        # mode has no fused program) is tunnel-dispatch-bound at ~120 ms/
        # step, so the full 20-epoch protocol (~6000 steps) cannot fit a
        # short window — record the 2-epoch --quick variant instead.
        run_bench zero_run --zero --quick && echo "[$(stamp)] zero: $(promote zero_run zero)"
        # Beyond-parity family row: the ViT fused whole run (own metric,
        # own file, same min-by-value promotion).
        echo "[$(stamp)] vit bench"
        # Outer bound must cover the tool's own worst case (120 s device
        # probe + 300 s run watchdog + margin) so the tool's structured
        # error JSON always gets written before SIGTERM — same rationale
        # as run_bench's BENCH_TIMEOUT_S+180.
        timeout 480 python "$REPO/tools/vit_bench.py" \
            >"$OUT/bench_r3_vit_run.json" 2>"$OUT/bench_r3_vit_run.err" \
            && echo "[$(stamp)] vit: $(promote vit_run vit)" \
            || echo "[$(stamp)] vit bench failed rc=$?"
        echo "[$(stamp)] flash-attention micro-bench"
        # 12 compiles (3 shapes x fwd/flash x +grad pairs) through the
        # tunnel: bound generously.
        timeout 540 python "$REPO/tools/flash_bench.py" --grad \
            >"$OUT/bench_r3_flash.json" 2>"$OUT/bench_r3_flash.err" \
            && echo "[$(stamp)] flash: $(cat "$OUT/bench_r3_flash.json")" \
            || echo "[$(stamp)] flash bench failed rc=$?"
        echo "[$(stamp)] pallas micro-bench"
        python "$REPO/tools/pallas_opt_bench.py" \
            >"$OUT/bench_r3_pallas_micro.json" 2>"$OUT/bench_r3_pallas_micro.err" \
            && echo "[$(stamp)] micro: $(cat "$OUT/bench_r3_pallas_micro.json")" \
            || echo "[$(stamp)] micro-bench failed rc=$?"
        # Attribution artifacts (verdict item 3): one per-batch step-stats
        # run and one profiler trace, both 1 epoch.
        echo "[$(stamp)] step-stats + profile capture"
        timeout 300 python "$REPO/mnist_ddp.py" --epochs 1 --batch-size 200 \
            --step-stats >"$OUT/bench_r3_stepstats.log" 2>&1 || true
        timeout 300 python "$REPO/mnist_ddp.py" --epochs 1 --batch-size 200 \
            --fused --profile "$OUT/trace_r3" >"$OUT/bench_r3_profile.log" 2>&1 || true
        echo "[$(stamp)] window complete; continuing to poll (re-warm duty)"
        sleep "$POST_WINDOW_SLEEP_S"
    else
        sleep "$POLL_S"
    fi
done
