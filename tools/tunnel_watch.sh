#!/bin/bash
# Round-long accelerator-tunnel watcher (round-5: VERDICT items 1, 2, 6).
#
# The TPU tunnel on this host is up only in short windows (round 2: one
# 8-minute window in ~20 hours; round 3: ~80 s windows; round 4: none).
# This script polls cheaply and, the moment the chip answers, runs the
# window playbook in value order (headline first, the round-5 attribution
# ladders next, variants last) so a drop mid-window still lands the most
# important artifacts:
#   0. real-MNIST IDX fetch attempt (digest-verified; logged durably)
#   1. headline bench — re-warm + warm record (min-by-value promotion)
#   2. step-attribution ladders: f32, conv-impl variants (THE round-5
#      decision data: does GEMM-lowering conv1 move the 0.83 ms floor?)
#   3. fused-step profiler trace -> committed per-op attribution
#   4. flash-attention micro-bench + compiled-mode parity
#   5. ViT fused bench with run/compile/data attribution
#   6. variant rows: bf16, pallas-opt, pregather, conv-impl end-to-end,
#      syncbn, fused-zero, ViT sp/tp/pp modes, bf16 ladder, micro
# After each major group the artifacts are git-committed: machine resets
# wipe uncommitted files (round 3 lost the 47 MB trace this way), so
# durability means a commit, not a file.
#
# Usage: nohup bash tools/tunnel_watch.sh >>/tmp/tunnel_watch_r5.log 2>&1 &
# NEVER edit this file while an instance runs (bash re-reads mid-execution):
# kill, edit, relaunch.
set -u
cd "$(dirname "$0")/.."
REPO="$PWD"
OUT="$REPO"
# Poll cadence vs window length: dead probes consume their FULL timeout
# (measured 95 s at the old bound), so the detection cycle was
# probe+sleep ~155 s while round-5 windows run ~2 min — entire windows
# could open and close between polls.  Live probes answer in ~3 s
# (measured twice this round), so 45 s classification + 20 s sleep gives
# a ~65 s worst-case detection cycle with >10x margin on the live case;
# a marginal tunnel misread as dead is re-probed 20 s later.
POLL_S=${POLL_S:-20}
PROBE_TIMEOUT_S=${PROBE_TIMEOUT_S:-45}
# Short post-playbook pause: tunnel throughput is bimodal, so every
# additional pass over a live window is a fresh draw at the FAST mode
# for every min-promoted row (the difference between a 26 s and a ~9 s
# recorded headline).  Re-runs of an already-complete playbook are cheap
# (warm cache, min-by-value promotion, commits only on change).
POST_WINDOW_SLEEP_S=${POST_WINDOW_SLEEP_S:-120}
BENCH_TIMEOUT_S=${BENCH_TIMEOUT_S:-240}

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

probe() {
    # -k 10: a dead-tunnel jax init can ignore SIGTERM (observed: a 50 s
    # probe still alive at 9m40) and timeout(1) without -k waits forever,
    # wedging the whole watcher loop on one probe.
    timeout -k 10 "$PROBE_TIMEOUT_S" python -c "import jax; d=jax.devices(); import sys; sys.exit(0 if d[0].platform != 'cpu' else 1)" \
        >/dev/null 2>&1
}

inwindow_probe() {
    # The ~10 per-window IN-PLAYBOOK liveness checks get one retry: a
    # live tunnel in the slow bimodal mode can exceed PROBE_TIMEOUT_S at
    # jax init, and a single misread aborts the playbook back to the
    # top, re-paying every completed leg (round-5 advisor).  The idle
    # polling loop keeps the single tight probe — there a false dead
    # just means the next poll 20 s later.
    probe && return 0
    echo "[$(stamp)] in-window probe missed ${PROBE_TIMEOUT_S}s — retrying once (slow-mode tunnel?)"
    probe
}

run_bench() { # $1 = tag, rest = extra bench.py args
    local tag="$1"; shift
    echo "[$(stamp)] bench $tag start"
    # Outer bound covers bench.py's probe (~90 s) + watchdog + margin so
    # the structured failure JSON is always written before SIGTERM.
    timeout -k 10 $((BENCH_TIMEOUT_S + 180)) \
        python "$REPO/bench.py" --probe-attempts 1 --run-timeout "$BENCH_TIMEOUT_S" "$@" \
        >"$OUT/bench_r5_${tag}.json" 2>"$OUT/bench_r5_${tag}.err"
    local rc=$?
    echo "[$(stamp)] bench $tag rc=$rc: $(cat "$OUT/bench_r5_${tag}.json" 2>/dev/null | head -c 400)"
    return $rc
}

is_warm() { # $1 = tag; true if that run's JSON recorded a warm cache
    grep -q '"cache": "warm"' "$OUT/bench_r5_$1.json" 2>/dev/null
}

promote() { # $1 = src tag, $2 = dst tag; copy ONLY if src beats dst.
    # Min-by-value rule + rationale live (tested) in tools/window_promote.py.
    python "$REPO/tools/window_promote.py" value \
        "$OUT/bench_r5_$1.json" "$OUT/bench_r5_$2.json"
}

ladder() { # $1 = tag suffix, rest = extra step_attr_bench.py args
    local tag="$1"; shift
    echo "[$(stamp)] step-attribution ladder ($tag)"
    # ~11 rungs x ~20 s cold compile each through the tunnel on the first
    # window; the persistent cache makes later windows warm.  -k 30: the
    # tool traps SIGTERM (partial flush), so a process wedged inside a
    # native XLA call would otherwise never die — escalate to SIGKILL.
    timeout -k 30 600 python "$REPO/tools/step_attr_bench.py" "$@" \
        >"$OUT/bench_r5_stepattr_${tag}.json" 2>"$OUT/bench_r5_stepattr_${tag}.err"
    local rc=$?
    echo "[$(stamp)] stepattr-$tag rc=$rc: $(head -c 400 "$OUT/bench_r5_stepattr_${tag}.json" 2>/dev/null)"
    return $rc
}

commit_artifacts() { # $1 = note.  Durability = a commit, not a file.
    ( cd "$REPO" || exit 1
      # Each path group added separately and force-added (-f): a missing
      # file or a stray ignore rule must not abort staging of the rest
      # (a single `git add a b c` exits 128 on the first unmatched
      # pathspec and stages NOTHING — round-4 review finding).
      for p in bench_r5_*.json bench_r5_*.err bench_last_good.json \
               data/idx_attempts.log; do
          git add -f -- "$p" 2>/dev/null || true
      done
      # Commit only if the index actually changed; retry once on a lock
      # race with an interactive session.  The success line is gated on
      # the commit's exit status (round-4 advisor: an unconditional echo
      # claimed durability while the artifacts stayed reset-volatile).
      if ! git diff --cached --quiet 2>/dev/null; then
          if git commit -q -m "watcher: tunnel-window artifacts ($1)" \
              || { sleep 20; git commit -q -m "watcher: tunnel-window artifacts ($1)"; }; then
              echo "[$(stamp)] committed artifacts ($1)"
          else
              echo "[$(stamp)] artifact commit FAILED ($1) — retry next group"
          fi
      fi ) || echo "[$(stamp)] artifact commit FAILED ($1)"
}

echo "[$(stamp)] r5 watcher up, polling every ${POLL_S}s"
while true; do
    if probe; then
        echo "[$(stamp)] TUNNEL UP — window playbook"
        # --- 0: real-MNIST attempt.  Worst case is 4 files x 2 mirrors x
        # 20 s hanging urlopens = ~160 s; the bound must cover it so the
        # attempt log line is written before any SIGTERM (review finding).
        timeout -k 10 200 python "$REPO/tools/fetch_mnist.py" \
            && echo "[$(stamp)] IDX FILES LANDED" \
            || echo "[$(stamp)] idx fetch failed (logged)"
        # --- 1: headline ------------------------------------------------
        run_bench warmup || { commit_artifacts "failed warmup"; sleep "$POLL_S"; continue; }
        # The persistent XLA cache survives between windows: if the first
        # run was already warm, promote it and spend the window elsewhere.
        if is_warm warmup; then
            echo "[$(stamp)] warmup ran warm — $(promote warmup warm)"
        else
            run_bench warm_run || { commit_artifacts "failed warm"; sleep "$POLL_S"; continue; }
            if is_warm warm_run; then
                echo "[$(stamp)] $(promote warm_run warm)"
            fi
        fi
        commit_artifacts "headline"
        # Windows can be ~2 min (round-5 first window: headline landed,
        # then the tunnel died and the f32 ladder hung 600 s producing
        # nothing).  Re-probe between groups: a dead tunnel means abort
        # back to polling so the NEXT window starts at the top of the
        # value order instead of whatever leg the dead playbook reached.
        # Cost on a LIVE tunnel is ~3 s per probe (measured 08:30 this
        # round); only the dead case pays the PROBE_TIMEOUT_S timeout
        # (x2 with the in-window retry), and then the abort saves the
        # rest of a ~90 min dead playbook.  inwindow_probe retries once
        # so a slow-bimodal-mode live tunnel is not misread as dead
        # mid-playbook.
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after headline — back to polling"; sleep "$POLL_S"; continue; }
        # --- 2: the round-5 decision ladders ---------------------------
        # f32 baseline rungs, then the conv-lowering variants: adjacent
        # deltas attribute the ~0.83 ms/step floor and decide --conv-impl.
        # Committed after EACH ladder (a reset mid-group must not wipe a
        # completed one), and the unsuffixed copy perf_report reads is
        # refreshed only on a successful f32 run — a truncated later
        # artifact must never clobber a good committed baseline.
        ladder f32
        # Refresh the unsuffixed copy perf_report reads via the rung-count
        # rule (tools/window_promote.py): runs regardless of the ladder's
        # exit code — a SIGTERM-flushed partial exits 124 yet may hold
        # real rungs; a truncated partial never clobbers a more complete
        # committed baseline, but the FIRST partial still lands.
        python "$REPO/tools/window_promote.py" rungs \
            "$OUT/bench_r5_stepattr_f32.json" "$OUT/bench_r5_stepattr.json"
        commit_artifacts "ladder-f32"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after f32 ladder — back to polling"; sleep "$POLL_S"; continue; }
        ladder im2col_c1 --conv-impl im2col_c1
        commit_artifacts "ladder-im2col-c1"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after im2col_c1 ladder — back to polling"; sleep "$POLL_S"; continue; }
        ladder im2col --conv-impl im2col
        commit_artifacts "ladder-im2col"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after ladders — back to polling"; sleep "$POLL_S"; continue; }
        # Batch-scaling diagnostic: if full(batch=1000) us/step is ~flat
        # vs the f32 ladder's full(batch=200), the ~0.5 ms/step residue
        # is per-op/latency overhead inside the scan body (fix: fewer,
        # larger ops); if it scales ~5x, the step is bandwidth/compute
        # bound and the floor is the model's shape.  60 steps keeps the
        # epoch-equivalent work bounded; --only spends two compiles (the
        # consumed rung + the overhead/compute split), not ten.
        # Promoted via the SAME rungs rule (full-rung tie-break) as the
        # f32 baseline: perf_report's batch-scaling verdict divides
        # b1000 full by baseline full, and with the documented 3.8x
        # bimodal throughput swing that ratio is only meaningful when
        # BOTH sides are cross-window minima (docs/PERF.md rule 2) —
        # a latest-wins slow-mode b1000 row against a min-promoted
        # baseline falsely flips the verdict (round-5 advisor).
        ladder b1000_run --batch 1000 --steps 60 --only full,fwd_bwd
        python "$REPO/tools/window_promote.py" rungs \
            "$OUT/bench_r5_stepattr_b1000_run.json" "$OUT/bench_r5_stepattr_b1000.json"
        commit_artifacts "ladder-b1000"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after b1000 ladder — back to polling"; sleep "$POLL_S"; continue; }
        # --- 3: fused-step trace -> per-op attribution ------------------
        # The trace itself is huge and reset-volatile: keep it in /tmp and
        # commit only the distilled attribution JSON.
        echo "[$(stamp)] fused trace capture + attribution"
        timeout -k 10 300 python "$REPO/mnist_ddp.py" --fused --epochs 2 \
            --batch-size 200 --profile /tmp/trace_r5 \
            >/tmp/trace_r5_run.log 2>&1 \
            && timeout -k 10 120 python "$REPO/tools/trace_attr.py" /tmp/trace_r5 \
                --out "$OUT/bench_r5_attr.json" \
                >>"$OUT/bench_r5_attr.json.err" 2>&1 \
            && echo "[$(stamp)] attr: $(head -c 400 "$OUT/bench_r5_attr.json")" \
            || echo "[$(stamp)] trace/attr failed rc=$? (see /tmp/trace_r5_run.log)"
        commit_artifacts "trace-attr"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after trace — back to polling"; sleep "$POLL_S"; continue; }
        # --- 4: flash kernel on hardware --------------------------------
        echo "[$(stamp)] flash-attention bench + compiled parity"
        # Outer bound > the tool's own --budget-s soft limit (it skips
        # remaining shapes once over budget and still prints its JSON);
        # per-shape try/except keeps earlier rows on an OOM at one shape.
        timeout -k 10 900 python "$REPO/tools/flash_bench.py" --grad --parity --budget-s 700 \
            >"$OUT/bench_r5_flash.json" 2>"$OUT/bench_r5_flash.err" \
            && echo "[$(stamp)] flash: $(head -c 400 "$OUT/bench_r5_flash.json")" \
            || echo "[$(stamp)] flash bench failed rc=$?"
        # --- 5: ViT fused bench with attribution ------------------------
        echo "[$(stamp)] vit bench"
        timeout -k 10 480 python "$REPO/tools/vit_bench.py" \
            >"$OUT/bench_r5_vit_run.json" 2>"$OUT/bench_r5_vit_run.err" \
            && echo "[$(stamp)] vit: $(promote vit_run vit)" \
            || echo "[$(stamp)] vit bench failed rc=$?"
        commit_artifacts "flash+vit"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after flash+vit — back to polling"; sleep "$POLL_S"; continue; }
        # --- 6: variant rows (each min-by-value) ------------------------
        run_bench bf16_run --bf16 && echo "[$(stamp)] bf16: $(promote bf16_run bf16)"
        run_bench pallas_run --pallas-opt && echo "[$(stamp)] pallas: $(promote pallas_run pallas)"
        # The pre-permuted-epoch input path (bit-identical batches, HLO
        # differs): decision row for flipping the headline's input path.
        run_bench pregather_run --pregather && echo "[$(stamp)] pregather: $(promote pregather_run pregather)"
        # End-to-end conv-lowering rows (pair with the ladder rungs above
        # before any default flip).
        run_bench conv_c1_run --conv-impl im2col_c1 && echo "[$(stamp)] conv_c1: $(promote conv_c1_run conv_c1)"
        run_bench conv_all_run --conv-impl im2col && echo "[$(stamp)] conv_all: $(promote conv_all_run conv_all)"
        # The combined candidate: if both independent flips win, the new
        # headline would run them together — measure the composition
        # directly (its ladder analogue is the im2col_c1 ladder's
        # full_pregather rung).
        run_bench conv_c1_pregather_run --conv-impl im2col_c1 --pregather \
            && echo "[$(stamp)] conv_c1+pregather: $(promote conv_c1_pregather_run conv_c1_pregather)"
        run_bench syncbn_run --syncbn && echo "[$(stamp)] syncbn: $(promote syncbn_run syncbn)"
        # ZeRO-1 now rides the fused whole-run (round-5): a full-protocol
        # row is one compile + one dispatch, same as the headline.
        run_bench zero_run --zero && echo "[$(stamp)] zero: $(promote zero_run zero)"
        # Commit the nine variant rows BEFORE the ~40-min vit/bf16 tail:
        # a reset mid-tail must not wipe them (durability = a commit).
        commit_artifacts "variant rows"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after variant rows — back to polling"; sleep "$POLL_S"; continue; }
        # ViT mode smoke rows: every shipped mode gets at least one
        # hardware number.  2-epoch quick protocol per mode.
        for mode in sp sp-ulysses tp flash zero; do
            echo "[$(stamp)] vit mode smoke: $mode"
            timeout -k 10 480 python "$REPO/tools/vit_bench.py" --mode "$mode" --epochs 2 \
                >"$OUT/bench_r5_vit_${mode}_run.json" 2>"$OUT/bench_r5_vit_${mode}_run.err" \
                && echo "[$(stamp)] vit-$mode: $(promote "vit_${mode}_run" "vit_$mode")" \
                || echo "[$(stamp)] vit-$mode failed rc=$?"
        done
        commit_artifacts "vit mode rows"
        inwindow_probe || { echo "[$(stamp)] TUNNEL LOST after vit modes — back to polling"; sleep "$POLL_S"; continue; }
        # The bf16 ladder (explains why --bf16 moved run_s only 4%).
        ladder bf16 --bf16
        # Pallas optimizer micro-benchmark (decision data for the kernel).
        python "$REPO/tools/pallas_opt_bench.py" \
            >"$OUT/bench_r5_pallas_micro.json" 2>"$OUT/bench_r5_pallas_micro.err" \
            && echo "[$(stamp)] micro: $(cat "$OUT/bench_r5_pallas_micro.json")" \
            || echo "[$(stamp)] micro-bench failed rc=$?"
        # Distill everything this window produced into docs/PERF.md's
        # results section and commit it: the analysis lands even if no
        # interactive session is alive when this window opens.
        timeout -k 10 60 python "$REPO/tools/perf_report.py" \
            >>"$OUT/bench_r5_perf_report.log" 2>&1 \
            && ( cd "$REPO" && git add docs/PERF.md 2>/dev/null ) \
            && echo "[$(stamp)] perf report appended" \
            || echo "[$(stamp)] perf report skipped rc=$?"
        commit_artifacts "variants"
        echo "[$(stamp)] window complete; continuing to poll (re-warm duty)"
        sleep "$POST_WINDOW_SLEEP_S"
    else
        sleep "$POLL_S"
    fi
done
