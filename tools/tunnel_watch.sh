#!/bin/bash
# Round-long accelerator-tunnel watcher (round-3 verdict, next-round items
# 1-4 and 6).
#
# The TPU tunnel on this host is up only in short windows (round 2: one
# 8-minute window in ~20 hours; round 3: ~80 s windows).  This script polls
# cheaply and, the moment the chip answers, runs the window playbook in
# value order (headline first, evidence-gap fillers next, variants last)
# so a drop mid-window still lands the most important artifacts:
#   0. real-MNIST IDX fetch attempt (verdict item 3; logged durably)
#   1. headline bench — re-warm + warm record (min-by-value promotion)
#   2. flash-attention micro-bench + compiled-mode parity (verdict item 2)
#   3. ViT fused bench with run/compile/data attribution (verdict item 4)
#   4. fused-step profiler trace -> committed per-op attribution (item 1)
#   5. variant rows: bf16, pallas-opt, syncbn, zero-quick, ViT sp/tp/pp
# After each major group the artifacts are git-committed: machine resets
# wipe uncommitted files (round 3 lost the 47 MB trace this way), so
# durability means a commit, not a file.
#
# Usage: nohup bash tools/tunnel_watch.sh >>/tmp/tunnel_watch_r4.log 2>&1 &
# NEVER edit this file while an instance runs (bash re-reads mid-execution):
# kill, edit, relaunch.
set -u
cd "$(dirname "$0")/.."
REPO="$PWD"
OUT="$REPO"
POLL_S=${POLL_S:-60}
POST_WINDOW_SLEEP_S=${POST_WINDOW_SLEEP_S:-900}
BENCH_TIMEOUT_S=${BENCH_TIMEOUT_S:-240}

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

probe() {
    timeout 95 python -c "import jax; d=jax.devices(); import sys; sys.exit(0 if d[0].platform != 'cpu' else 1)" \
        >/dev/null 2>&1
}

run_bench() { # $1 = tag, rest = extra bench.py args
    local tag="$1"; shift
    echo "[$(stamp)] bench $tag start"
    # Outer bound covers bench.py's probe (~90 s) + watchdog + margin so
    # the structured failure JSON is always written before SIGTERM.
    timeout $((BENCH_TIMEOUT_S + 180)) \
        python "$REPO/bench.py" --probe-attempts 1 --run-timeout "$BENCH_TIMEOUT_S" "$@" \
        >"$OUT/bench_r4_${tag}.json" 2>"$OUT/bench_r4_${tag}.err"
    local rc=$?
    echo "[$(stamp)] bench $tag rc=$rc: $(cat "$OUT/bench_r4_${tag}.json" 2>/dev/null | head -c 400)"
    return $rc
}

is_warm() { # $1 = tag; true if that run's JSON recorded a warm cache
    grep -q '"cache": "warm"' "$OUT/bench_r4_$1.json" 2>/dev/null
}

promote() { # $1 = src tag, $2 = dst tag; copy ONLY if src beats dst.
    # Tunnel throughput is bimodal (9.3 s vs 61.8 s for the same warm
    # program minutes apart): every recorded row is min-by-value, never
    # latest-wins.  The .err sidecar travels with its json.
    python - "$OUT/bench_r4_$1" "$OUT/bench_r4_$2" <<'EOF'
import json, os, shutil, sys
src, dst = sys.argv[1], sys.argv[2]
new = json.load(open(src + ".json"))["value"]
try:
    old = json.load(open(dst + ".json"))["value"]
except Exception:
    old = None
if old is None or (new is not None and new < old):
    shutil.copy(src + ".json", dst + ".json")
    if os.path.exists(src + ".err"):
        shutil.copy(src + ".err", dst + ".err")
    print(f"promoted {new} (previous {old})")
else:
    print(f"kept {old} (new run {new} is slower)")
EOF
}

commit_artifacts() { # $1 = note.  Durability = a commit, not a file.
    ( cd "$REPO" || exit 1
      # Each path group added separately and force-added (-f): a missing
      # file or a stray ignore rule must not abort staging of the rest
      # (a single `git add a b c` exits 128 on the first unmatched
      # pathspec and stages NOTHING — round-4 review finding).
      for p in bench_r4_*.json bench_r4_*.err bench_last_good.json \
               data/idx_attempts.log; do
          git add -f -- "$p" 2>/dev/null || true
      done
      # Commit only if the index actually changed; retry once on a lock
      # race with an interactive session.
      if ! git diff --cached --quiet 2>/dev/null; then
          git commit -q -m "watcher: tunnel-window artifacts ($1)" \
              || { sleep 20; git commit -q -m "watcher: tunnel-window artifacts ($1)"; }
          echo "[$(stamp)] committed artifacts ($1)"
      fi ) || echo "[$(stamp)] artifact commit failed ($1)"
}

echo "[$(stamp)] r4 watcher up, polling every ${POLL_S}s"
while true; do
    if probe; then
        echo "[$(stamp)] TUNNEL UP — window playbook"
        # --- 0: real-MNIST attempt.  Worst case is 4 files x 2 mirrors x
        # 20 s hanging urlopens = ~160 s; the bound must cover it so the
        # attempt log line is written before any SIGTERM (review finding).
        timeout 200 python "$REPO/tools/fetch_mnist.py" \
            && echo "[$(stamp)] IDX FILES LANDED" \
            || echo "[$(stamp)] idx fetch failed (logged)"
        # --- 1: headline ------------------------------------------------
        run_bench warmup || { commit_artifacts "failed warmup"; sleep "$POLL_S"; continue; }
        # The persistent XLA cache survives between windows: if the first
        # run was already warm, promote it and spend the window elsewhere.
        if is_warm warmup; then
            echo "[$(stamp)] warmup ran warm — $(promote warmup warm)"
        else
            run_bench warm_run || { commit_artifacts "failed warm"; sleep "$POLL_S"; continue; }
            if is_warm warm_run; then
                echo "[$(stamp)] $(promote warm_run warm)"
            fi
        fi
        commit_artifacts "headline"
        # --- 2: flash kernel on hardware (verdict item 2) ---------------
        echo "[$(stamp)] flash-attention bench + compiled parity"
        # Outer bound > the tool's own --budget-s soft limit (it skips
        # remaining shapes once over budget and still prints its JSON):
        # a SIGTERM here would discard ALL rows, the worse failure.
        timeout 900 python "$REPO/tools/flash_bench.py" --grad --parity --budget-s 700 \
            >"$OUT/bench_r4_flash.json" 2>"$OUT/bench_r4_flash.err" \
            && echo "[$(stamp)] flash: $(head -c 400 "$OUT/bench_r4_flash.json")" \
            || echo "[$(stamp)] flash bench failed rc=$?"
        # --- 3: ViT fused bench with attribution (verdict item 4) -------
        echo "[$(stamp)] vit bench"
        timeout 480 python "$REPO/tools/vit_bench.py" \
            >"$OUT/bench_r4_vit_run.json" 2>"$OUT/bench_r4_vit_run.err" \
            && echo "[$(stamp)] vit: $(promote vit_run vit)" \
            || echo "[$(stamp)] vit bench failed rc=$?"
        commit_artifacts "flash+vit"
        # --- 4a: step-variant decomposition ladder (verdict item 1):
        # warm per-step us for empty scan / gather / fwd / fwd+bwd /
        # full±dropout±gather — attributes the ~0.8 ms floor by
        # construction, independent of the trace path below.
        echo "[$(stamp)] step-attribution ladder"
        # 10 rungs x ~20 s cold compile each through the tunnel on the
        # first window; the persistent cache makes later windows warm.
        timeout 600 python "$REPO/tools/step_attr_bench.py" \
            >"$OUT/bench_r4_stepattr.json" 2>"$OUT/bench_r4_stepattr.err" \
            && echo "[$(stamp)] stepattr: $(head -c 400 "$OUT/bench_r4_stepattr.json")" \
            || echo "[$(stamp)] stepattr failed rc=$?"
        # --- 4: fused-step trace -> per-op attribution (verdict item 1) -
        # The trace itself is huge and reset-volatile: keep it in /tmp and
        # commit only the distilled attribution JSON.
        echo "[$(stamp)] fused trace capture + attribution"
        timeout 300 python "$REPO/mnist_ddp.py" --fused --epochs 2 \
            --batch-size 200 --profile /tmp/trace_r4 \
            >/tmp/trace_r4_run.log 2>&1 \
            && timeout 120 python "$REPO/tools/trace_attr.py" /tmp/trace_r4 \
                --out "$OUT/bench_r4_attr.json" \
                >>"$OUT/bench_r4_attr.json.err" 2>&1 \
            && echo "[$(stamp)] attr: $(head -c 400 "$OUT/bench_r4_attr.json")" \
            || echo "[$(stamp)] trace/attr failed rc=$? (see /tmp/trace_r4_run.log)"
        ( cd "$REPO" && git add bench_r4_attr.json 2>/dev/null ) || true
        commit_artifacts "trace-attr"
        # --- 5: variant rows (each min-by-value) ------------------------
        run_bench bf16_run --bf16 && echo "[$(stamp)] bf16: $(promote bf16_run bf16)"
        run_bench pallas_run --pallas-opt && echo "[$(stamp)] pallas: $(promote pallas_run pallas)"
        # The pre-permuted-epoch input path (bit-identical batches, HLO
        # differs): decision row for flipping the headline's input path.
        run_bench pregather_run --pregather && echo "[$(stamp)] pregather: $(promote pregather_run pregather)"
        run_bench syncbn_run --syncbn && echo "[$(stamp)] syncbn: $(promote syncbn_run syncbn)"
        # ZeRO-1 per-batch dispatch through the tunnel is ~120 ms/step:
        # only the 2-epoch --quick protocol fits a short window.
        run_bench zero_run --zero --quick && echo "[$(stamp)] zero: $(promote zero_run zero)"
        # ViT mode smoke rows (verdict item 6): every shipped mode gets at
        # least one hardware number.  2-epoch quick protocol per mode.
        for mode in sp sp-ulysses tp flash zero; do
            echo "[$(stamp)] vit mode smoke: $mode"
            timeout 480 python "$REPO/tools/vit_bench.py" --mode "$mode" --epochs 2 \
                >"$OUT/bench_r4_vit_${mode}_run.json" 2>"$OUT/bench_r4_vit_${mode}_run.err" \
                && echo "[$(stamp)] vit-$mode: $(promote "vit_${mode}_run" "vit_$mode")" \
                || echo "[$(stamp)] vit-$mode failed rc=$?"
        done
        # The bf16 ladder (explains why --bf16 moved run_s only 4%).
        echo "[$(stamp)] step-attribution ladder (bf16)"
        timeout 600 python "$REPO/tools/step_attr_bench.py" --bf16 \
            >"$OUT/bench_r4_stepattr_bf16.json" 2>"$OUT/bench_r4_stepattr_bf16.err" \
            && echo "[$(stamp)] stepattr-bf16: $(head -c 400 "$OUT/bench_r4_stepattr_bf16.json")" \
            || echo "[$(stamp)] stepattr-bf16 failed rc=$?"
        # Pallas optimizer micro-benchmark (decision data for the kernel).
        python "$REPO/tools/pallas_opt_bench.py" \
            >"$OUT/bench_r4_pallas_micro.json" 2>"$OUT/bench_r4_pallas_micro.err" \
            && echo "[$(stamp)] micro: $(cat "$OUT/bench_r4_pallas_micro.json")" \
            || echo "[$(stamp)] micro-bench failed rc=$?"
        # Distill everything this window produced into docs/PERF.md's
        # results section and commit it: the analysis lands even if no
        # interactive session is alive when this window opens.
        timeout 60 python "$REPO/tools/perf_report.py" \
            >>"$OUT/bench_r4_perf_report.log" 2>&1 \
            && ( cd "$REPO" && git add docs/PERF.md 2>/dev/null ) \
            && echo "[$(stamp)] perf report appended" \
            || echo "[$(stamp)] perf report skipped rc=$?"
        commit_artifacts "variants"
        echo "[$(stamp)] window complete; continuing to poll (re-warm duty)"
        sleep "$POST_WINDOW_SLEEP_S"
    else
        sleep "$POLL_S"
    fi
done
