"""Machine-checked SLO regression gate over the serving stack.

Every perf claim in ROADMAP items 2-3 was narrated, not asserted
(round-5 verdict's headline finding); this tool converts the serving
SLOs into a CI-runnable gate.  It replays the canned open-loop trace
committed in ``tools/slo_budgets.json`` (seeded Poisson arrivals +
seeded sizes — the trace is fully determined by the protocol block)
through ``tools/serve_loadgen.py`` against a virtual-device replica
pool (``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count``,
so the gate needs no accelerator), then asserts the budget table
**straight from the run's artifacts**:

==========================  =============================================
budget                      asserted from
==========================  =============================================
client p99                  the loadgen report (open-loop, coordinated-
                            omission-free latency)
server p99                  telemetry JSONL ``serving_request`` events
batch fill ratio (mean)     Prometheus dump ``serving_batch_fill_ratio``
                            ``_sum``/``_count``
pipeline stall (total s)    Prometheus dump ``serving_pipeline_stall_
                            seconds_sum``
zero post-warmup compiles   Prometheus dump ``jax_compiles_total`` ==
                            replicas x rungs (the warmup grid, exactly;
                            rungs = the pow2 ladder, or the collapsed
                            packed capacity ladder when the protocol
                            sets ``"packed": true``)
                            + the report's ``additional_compiles``
recovery (mean s, count)    recovery-round telemetry ``replica_restart``
                            events under the committed chaos clause
zero-downtime weight swap   swap-round registry report (loadgen
                            ``--swap-at-s`` + ``--canary-sweep``): zero
                            lost/torn responses, zero added compiles,
                            the new weights actually served
==========================  =============================================

Each run appends one row to the committed ``BENCH_slo.json`` trajectory
(measured values + verdict), so the SLO history is diffable like every
other BENCH artifact.

``--inject p99`` arms the committed regression schedule (per-dispatch
hang on every replica — a server that got slower) and skips the
recovery round: the gate must then exit non-zero with a p99 breach,
which is how CI proves the gate actually bites (the ``slo`` job runs it
both ways).

Usage:
    python tools/slo_gate.py [--budgets tools/slo_budgets.json]
        [--trajectory BENCH_slo.json] [--no-append] [--inject p99]
        [--workdir DIR] [--keep]

Exit 0 = every budget met; 1 = at least one budget breached (or a
round's loadgen verdict failed); 2 = infrastructure/usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _read_prom(path: str) -> dict[str, float]:
    """Flat ``{sample_name{labels}: value}`` map of a Prometheus text
    exposition (comments skipped); the gate reads raw samples, not a
    scrape library's interpretation."""
    out: dict[str, float] = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                name, _, value = line.rpartition(" ")
                try:
                    out[name] = float(value)
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def _prom_sum(prom: dict[str, float], family: str) -> float:
    """Sum every sample of ``family`` across label sets (exact name or
    ``family{...}``)."""
    pat = re.compile(re.escape(family) + r"(\{|$)")
    return sum(v for k, v in prom.items() if pat.match(k))


def _read_events(directory: str) -> list[dict]:
    import glob

    from pytorch_mnist_ddp_tpu.obs.events import read_events

    events: list[dict] = []
    for path in sorted(glob.glob(os.path.join(directory, "*.jsonl"))):
        events.extend(read_events(path))
    return events


def _percentile(sorted_values: list[float], q: float) -> float:
    from pytorch_mnist_ddp_tpu.obs.registry import percentile

    return percentile(sorted_values, q)


def _run_loadgen(label: str, cli_args: list[str], devices: int,
                 timeout_s: float = 600.0) -> int:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}"
    ).strip()
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve_loadgen.py")]
    cmd += cli_args
    print(f"slo_gate [{label}]: {' '.join(cmd)}", flush=True)
    proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout_s)
    return proc.returncode


def run_gate(args) -> int:
    with open(args.budgets) as f:
        spec = json.load(f)
    protocol, budgets = spec["protocol"], spec["budgets"]
    injected = args.inject
    workdir = args.workdir or tempfile.mkdtemp(prefix="slo_gate_")
    os.makedirs(workdir, exist_ok=True)
    devices = int(protocol["virtual_devices"])
    replicas = int(protocol["replicas"])
    buckets = [int(b) for b in str(protocol["buckets"]).split(",")]
    packed = bool(protocol.get("packed"))
    if packed:
        # The engines collapse the pow2 ladder to the packed
        # rows-capacity ladder (serving/buckets.packed_capacities), so
        # the warmup-grid arithmetic below must count CAPACITIES — an
        # expected-compiles figure computed from the pre-collapse ladder
        # would flag the collapse itself as a breach.
        from pytorch_mnist_ddp_tpu.serving.buckets import packed_capacities

        rungs = list(packed_capacities(max(buckets), 1))
    else:
        rungs = buckets

    common = [
        "--open-loop",
        "--rate", str(protocol["rate_rps"]),
        "--requests", str(protocol["requests"]),
        "--max-request", str(protocol["max_request"]),
        "--buckets", str(protocol["buckets"]),
        "--replicas", str(replicas),
        "--seed", str(protocol["seed"]),
        "--timeout-s", str(protocol.get("client_timeout_s", 30)),
    ]
    if packed:
        common += ["--packed"]
        if protocol.get("fill_wait_ms") is not None:
            common += ["--fill-wait-ms", str(protocol["fill_wait_ms"])]
    if protocol.get("replica_shapes"):
        # Heterogeneous pool: sharded replicas (tp/ep/pp) span device
        # blocks and are parity-gated at warmup; the budgets must hold
        # with them in the pool, not only for per-device dp replicas.
        common += ["--replica-shapes", str(protocol["replica_shapes"])]

    # -- round 1: the steady-state trace --------------------------------------
    steady_report = os.path.join(workdir, "steady_report.json")
    steady_prom = os.path.join(workdir, "steady.prom")
    steady_tel = os.path.join(workdir, "steady_tel")
    steady_args = common + [
        "--report", steady_report,
        "--prom-dump", steady_prom,
        "--telemetry-dir", steady_tel,
    ]
    if injected == "p99":
        # The committed regression: every dispatch on every replica gets
        # slower (the chaos grammar's per-dispatch hang) and the server
        # deadline is opened up so requests complete slowly instead of
        # expiring — the p99 budget, not a 504 flood, must catch it.
        steady_args += [
            "--chaos", protocol["inject_p99_chaos"],
            "--chaos-seed", str(protocol.get("chaos_seed", 0)),
            "--chaos-max-503-rate", "1.0",
            "--chaos-stall-timeout", "30",
            "--timeout-ms", "20000",
        ]
    steady_rc = _run_loadgen("steady", steady_args, devices)

    measured: dict = {"steady_loadgen_rc": steady_rc}
    failures: list[str] = []
    try:
        with open(steady_report) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        print(f"slo_gate: steady round produced no report ({e})")
        return 2

    prom = _read_prom(steady_prom)
    events = _read_events(steady_tel)

    # p99, client side (open-loop scheduled-arrival latency — the
    # coordinated-omission-free number) and server side (JSONL).
    measured["client_p99_ms"] = float(report["latency_ms"]["p99"])
    server_lats = sorted(
        e["latency_s"] for e in events
        if e.get("event") == "serving_request" and "latency_s" in e
    )
    measured["server_p99_ms"] = (
        1e3 * _percentile(server_lats, 99) if server_lats else None
    )
    measured["goodput_rps"] = report.get("goodput_rps")

    # Fill ratio + stall, straight from the Prometheus dump.
    fill_sum = _prom_sum(prom, "serving_batch_fill_ratio_sum")
    fill_count = _prom_sum(prom, "serving_batch_fill_ratio_count")
    measured["mean_fill_ratio"] = (
        fill_sum / fill_count if fill_count else None
    )
    measured["stall_seconds_total"] = _prom_sum(
        prom, "serving_pipeline_stall_seconds_sum"
    )

    # Zero post-warmup compiles: the sentinel counter must hold EXACTLY
    # the warmup grid (replicas x rungs, f32 only in this protocol;
    # rungs = pow2 buckets, or the collapsed capacity ladder when the
    # protocol runs packed), and the report's delta must be zero.
    measured["jax_compiles_total"] = _prom_sum(prom, "jax_compiles_total")
    measured["expected_warmup_compiles"] = replicas * len(rungs)
    measured["additional_compiles"] = report.get("additional_compiles")

    def check(name: str, ok: bool, detail: str) -> None:
        verdict = "ok" if ok else "BREACH"
        print(f"slo_gate: {name:<28} {detail:<44} [{verdict}]")
        if not ok:
            failures.append(name)

    check(
        "client_p99_ms",
        measured["client_p99_ms"] <= budgets["client_p99_ms"],
        f"{measured['client_p99_ms']:.1f} <= {budgets['client_p99_ms']}",
    )
    check(
        "server_p99_ms",
        measured["server_p99_ms"] is not None
        and measured["server_p99_ms"] <= budgets["server_p99_ms"],
        f"{measured['server_p99_ms'] and round(measured['server_p99_ms'], 1)}"
        f" <= {budgets['server_p99_ms']}",
    )
    check(
        "mean_fill_ratio",
        measured["mean_fill_ratio"] is not None
        and measured["mean_fill_ratio"] >= budgets["min_mean_fill_ratio"],
        f"{measured['mean_fill_ratio'] and round(measured['mean_fill_ratio'], 3)}"
        f" >= {budgets['min_mean_fill_ratio']}",
    )
    check(
        "stall_seconds_total",
        measured["stall_seconds_total"] <= budgets["max_stall_seconds_total"],
        f"{measured['stall_seconds_total']:.3f} <= "
        f"{budgets['max_stall_seconds_total']}",
    )
    check(
        "post_warmup_compiles",
        measured["jax_compiles_total"] == measured["expected_warmup_compiles"]
        and measured["additional_compiles"] == 0,
        f"{measured['jax_compiles_total']:.0f} == "
        f"{measured['expected_warmup_compiles']} and delta "
        f"{measured['additional_compiles']} == 0",
    )
    if injected is None and steady_rc != 0:
        check("steady_loadgen_verdict", False, f"rc {steady_rc} != 0")

    # -- round 2: recovery under the committed chaos clause --------------------
    if injected is None:
        rec_report = os.path.join(workdir, "recovery_report.json")
        rec_tel = os.path.join(workdir, "recovery_tel")
        rec_rc = _run_loadgen(
            "recovery",
            common + [
                "--report", rec_report,
                "--telemetry-dir", rec_tel,
                "--chaos", protocol["recovery_chaos"],
                "--chaos-seed", str(protocol.get("chaos_seed", 0)),
                "--chaos-max-503-rate", "0.25",
                "--chaos-stall-timeout", "2.0",
            ],
            devices,
        )
        rec_events = _read_events(rec_tel)
        recoveries = [
            float(e["recovery_s"]) for e in rec_events
            if e.get("event") == "replica_restart"
            and e.get("outcome") == "restarted" and "recovery_s" in e
        ]
        measured["recovery_loadgen_rc"] = rec_rc
        measured["restarts"] = len(recoveries)
        measured["mean_recovery_s"] = (
            sum(recoveries) / len(recoveries) if recoveries else None
        )
        check(
            "recovery_restarts",
            measured["restarts"] >= budgets["min_restarts"],
            f"{measured['restarts']} >= {budgets['min_restarts']}",
        )
        check(
            "mean_recovery_s",
            measured["mean_recovery_s"] is not None
            and measured["mean_recovery_s"] <= budgets["max_mean_recovery_s"],
            f"{measured['mean_recovery_s'] and round(measured['mean_recovery_s'], 3)}"
            f" <= {budgets['max_mean_recovery_s']}",
        )
        check(
            "recovery_loadgen_verdict", rec_rc == 0, f"rc {rec_rc} == 0"
        )

    # -- round 3: zero-downtime weight swap + canary sweep ---------------------
    # Registry drive: a live /admin/swap fired mid-trace plus the
    # committed canary rungs, all on one engine (the drive owns its own
    # registry stack).  The budget is absolute: zero lost requests, zero
    # torn responses, zero post-warmup compiles — a weight swap that
    # drops or re-traces is an outage, not a degradation.
    if injected is None:
        swap_report_path = os.path.join(workdir, "registry_report.json")
        swap_rc = _run_loadgen(
            "swap",
            [
                "--swap-at-s", str(protocol.get("swap_at_s", 1.0)),
                "--canary-sweep", str(protocol.get("canary_pcts", "25,50")),
                "--requests", str(protocol["requests"]),
                "--max-request", str(protocol["max_request"]),
                "--buckets", str(protocol["buckets"]),
                "--seed", str(protocol["seed"]),
                "--timeout-s", str(protocol.get("client_timeout_s", 30)),
                "--registry-report", swap_report_path,
            ],
            devices=1,
        )
        try:
            with open(swap_report_path) as f:
                swap_report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"slo_gate: swap round produced no report ({e})")
            return 2
        swap = swap_report.get("swap", {})
        sweep = swap_report.get("canary_sweep", {})
        measured["swap_loadgen_rc"] = swap_rc
        measured["swap_requests"] = swap.get("requests")
        measured["swap_lost"] = swap.get("lost_or_failed")
        measured["swap_torn"] = swap.get("torn")
        measured["swap_served_new"] = swap.get("served_new")
        measured["swap_added_compiles"] = swap_report.get(
            "additional_compiles"
        )
        measured["canary_misrouted"] = sum(
            r.get("misrouted", 0) + r.get("failed", 0)
            for r in sweep.get("rungs", [])
        )
        check(
            "swap_lost_requests",
            measured["swap_lost"] == budgets["max_swap_lost"] == 0,
            f"{measured['swap_lost']} == 0",
        )
        check(
            "swap_torn_responses",
            measured["swap_torn"] == budgets["max_swap_torn"] == 0,
            f"{measured['swap_torn']} == 0",
        )
        check(
            "swap_served_new_weights",
            (measured["swap_served_new"] or 0) > 0,
            f"{measured['swap_served_new']} > 0",
        )
        check(
            "swap_added_compiles",
            measured["swap_added_compiles"]
            == budgets["max_swap_added_compiles"] == 0,
            f"{measured['swap_added_compiles']} == 0",
        )
        check(
            "canary_exact_split",
            measured["canary_misrouted"] == 0,
            f"{measured['canary_misrouted']} misrouted/failed == 0",
        )
        check("swap_loadgen_verdict", swap_rc == 0, f"rc {swap_rc} == 0")

    passed = not failures
    row = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "injected": injected,
        "pass": passed,
        "failures": failures,
        "measured": measured,
        "budgets": budgets,
        "protocol": protocol,
    }
    if not args.no_append:
        trajectory: list = []
        try:
            with open(args.trajectory) as f:
                trajectory = json.load(f)
                if not isinstance(trajectory, list):
                    trajectory = [trajectory]
        except (OSError, ValueError):
            trajectory = []
        trajectory.append(row)
        with open(args.trajectory, "w") as f:
            json.dump(trajectory, f, indent=2)
            f.write("\n")
        print(f"slo_gate: appended run to {args.trajectory}")
    if not args.keep and args.workdir is None:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    print(
        f"SLO GATE: {'PASS' if passed else 'FAIL'}"
        + (f" (breached: {', '.join(failures)})" if failures else "")
        + (f" [injected={injected}]" if injected else "")
    )
    return 0 if passed else 1


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument(
        "--budgets", default=os.path.join(REPO, "tools", "slo_budgets.json"),
        help="committed protocol + budget table (tools/slo_budgets.json)",
    )
    p.add_argument(
        "--trajectory", default=os.path.join(REPO, "BENCH_slo.json"),
        help="committed SLO trajectory this run appends to",
    )
    p.add_argument(
        "--no-append", action="store_true",
        help="don't append this run to the trajectory (the CI "
        "injected-regression proof uses this)",
    )
    p.add_argument(
        "--inject", default=None, choices=("p99",),
        help="arm the committed regression schedule; the gate must then "
        "FAIL — the CI job's proof that the gate bites",
    )
    p.add_argument(
        "--workdir", default=None,
        help="where the run artifacts land (default: a temp dir, "
        "removed unless --keep)",
    )
    p.add_argument("--keep", action="store_true",
                   help="keep the artifacts directory")
    args = p.parse_args(argv)
    try:
        return run_gate(args)
    except subprocess.TimeoutExpired as e:
        print(f"slo_gate: round timed out: {e}")
        return 2


if __name__ == "__main__":
    sys.exit(main())
