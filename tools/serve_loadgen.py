#!/usr/bin/env python
"""Load generator for the serving subsystem (docs/SERVING.md).

Fires mixed-size /predict requests from concurrent client threads at a
serving endpoint and writes a ``BENCH_serving.json``-style report:
client-side p50/p95/p99 latency, throughput, per-status counts
(including 503 rejections — the backpressure signal), and the server's
own /metrics snapshot before and after the run.

The headline assertion is the retrace firewall: mixed request sizes must
cause ZERO additional compiles beyond the warmed buckets.  The tool
reads the server's ``compiles`` gauge before and after and exits nonzero
if it moved (disable with --no-check-compiles when deliberately probing
an unwarmed ladder).

Two arrival models:

- **closed loop** (default): ``--concurrency`` client threads, each
  firing its next request when the previous answers.  Simple, but the
  server's own latency throttles the offered load — a pipelining win
  shows up as lower latency, not higher pressure.
- **open loop** (``--open-loop``): requests arrive on a Poisson process
  at ``--rate`` req/s *regardless of completions*, the arrival model
  real traffic actually has (and the one that exposes overlap: the
  server must absorb arrivals while earlier batches are still in
  flight).  Offered vs achieved rate both land in the report.

Default mode (``--self-serve``) spins the whole stack up in-process on a
loopback port with fresh seed weights — no checkpoint, no running server,
no network needed: the CI-able smoke path.  Point --url at a real server
to load-test a deployment.  ``--prom-dump PATH`` saves the endpoint's
final Prometheus exposition (the in-flight gauge, stall/fill histograms)
for offline grepping — the CI smoke's hook.

Scale-out (docs/SERVING.md): ``--replicas N`` self-serves an N-replica
per-device engine pool behind the queue-aware router
(``--router-policy``), and ``--replicas-sweep 1,2,4`` runs the same
workload against each count in turn, writing goodput vs. replicas at
fixed p99 plus scaling efficiency to ``BENCH_serving_scaleout.json``.

Tail-latency mode (docs/SERVING.md QoS section): ``--qos-mix
interactive=0.8,batch=0.2`` labels every request with a seeded QoS
class (the ``/predict`` ``"qos"`` field) and the report gains per-class
latency percentiles; ``--hedge`` / ``--hedge-delay-ms`` enable hedged
dispatch on the self-serve pool; and ``--ab-tail`` drives the SAME
open-loop trace against a feature-off and a feature-on pool, writing
per-class p50/p95/p99 deltas to ``BENCH_tail.json`` and FAILING on any
lost response or duplicated client-visible outcome.

Chaos mode (docs/ROBUSTNESS.md): ``--chaos SPEC`` arms a fault schedule
(``fail:launch:r1:count=6;hang:complete:r0:for=2``) against the
self-serve pool while the workload runs, then FAILS the run on any lost
or duplicated response, any transport error, a 503 rate above
``--chaos-max-503-rate``, an unrecovered replica, or any post-restart
compile — and writes restarts, recovery times, circuit states, and the
fault receipt into the report's ``chaos`` section.  This is the
operator-facing proof that the supervisor + circuit breakers actually
absorb the failure classes they claim to.

Host hot path (docs/SERVING.md): ``--wire {json,binary}`` picks the
request format (binary = ``application/x-mnist-f32``, serving/wire.py;
bodies are pre-encoded BEFORE the arrival clock in both formats, so the
measured window never contains request serialization), ``--repeat-dist
zipf:S[:K]`` draws payloads from a seeded zipf-popularity catalog (the
response-cache hit distribution), ``--response-cache N`` enables the
self-serve server's cache tier, and ``--hostpath-ab`` runs the whole
A/B — same open-loop trace per wire format at equal offered rate, then
a zipf cache round — into ``BENCH_hostpath.json``, failing on any lost
or duplicated response, post-warmup compile, zero cache hits, or a
hit-path p99 not under the miss-path p99.

Usage::

    python tools/serve_loadgen.py                       # self-contained
    python tools/serve_loadgen.py --open-loop --rate 500 --requests 1000
    python tools/serve_loadgen.py --url http://host:8000 \
        --requests 2000 --concurrency 32
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fetch_json(url: str, payload: dict | None = None, timeout: float = 30.0) -> tuple[int, dict]:
    """One HTTP exchange -> (status, parsed body); HTTP errors are data
    here (503 IS the backpressure measurement), so they don't raise.
    Transport-level failures (connection refused/reset, timeout) return
    status 0 — under --chaos a lost RESPONSE is precisely the defect the
    harness asserts against, so it must be countable, not a dead client
    thread silently shrinking the result set."""
    req = urllib.request.Request(
        url,
        data=None if payload is None else json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        try:
            body = json.load(e)
        except Exception:
            body = {}
        return e.code, body
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        return 0, {"error": str(e)}


def fetch_raw(
    url: str, body: bytes, headers: dict, timeout: float = 30.0
) -> tuple[int, bytes]:
    """Transport-only /predict exchange for a PRE-ENCODED body.

    The drive loops send through here so the latency-measured window
    contains zero request serialization work — bodies are built once,
    before the arrival clock starts (the per-request re-encode audit,
    pinned by tests/test_hostpath.py).  Same status-0-on-transport-error
    contract as :func:`fetch_json`."""
    req = urllib.request.Request(url, data=body, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        try:
            data = e.read()
        except Exception:
            data = b""
        return e.code, data
    except (urllib.error.URLError, OSError, TimeoutError):
        return 0, b""


def fetch_text(url: str, timeout: float = 30.0) -> str:
    """GET a text body (the Prometheus exposition for --prom-dump)."""
    req = urllib.request.Request(url, headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode()


def _encode_body(
    pixels: list, wire_fmt: str, dtype: str, qos: str | None,
    log_probs: bool = False,
) -> tuple[bytes, dict]:
    """One request's (body bytes, headers) — the SINGLE request-encode
    funnel.  Every body is built through here at PLAN time, before the
    arrival clock starts; the drive loops only move bytes (the
    re-encode-in-window audit, tests/test_hostpath.py).

    ``log_probs`` asks the JSON server for the full per-class logits —
    the equal-information response to the binary wire's raw logits
    bytes (the hostpath A/B sets it on the JSON rung so neither format
    answers with less than the other)."""
    if wire_fmt == "binary":
        import numpy as np

        from pytorch_mnist_ddp_tpu.serving import wire

        body = wire.encode_request(
            np.asarray(pixels, np.float32), dtype=dtype, qos=qos
        )
        return body, {"Content-Type": wire.WIRE_REQUEST_TYPE}
    payload = {"instances": pixels}
    if log_probs:
        payload["return_log_probs"] = True
    if dtype != "f32":
        # The reduced-precision A/B knob (docs/SERVING.md): route every
        # request to one named variant; the default payload stays
        # byte-compatible with pre-dtype servers.
        payload["dtype"] = dtype
    if qos is not None:
        # The tail-latency A/B knob: name the scheduling class.  Omitted
        # = interactive (the server default), so pre-QoS payloads are
        # unchanged.
        payload["qos"] = qos
    return json.dumps(payload).encode(), {"Content-Type": "application/json"}


def _parse_repeat_dist(spec: str) -> tuple[float, int]:
    """``zipf:S[:K]`` -> (exponent, catalog size).  Rank r of K distinct
    payloads is drawn with probability proportional to r^-S — the
    classic popularity skew a response cache actually meets (S ~ 1 is
    web-like; bigger = spikier).  Default catalog 16."""
    parts = spec.split(":")
    if parts[0] != "zipf" or len(parts) not in (2, 3):
        raise SystemExit(
            f"--repeat-dist {spec!r} must be zipf:S or zipf:S:K "
            "(S = exponent, K = distinct payloads)"
        )
    try:
        s_exp = float(parts[1])
        catalog = int(parts[2]) if len(parts) == 3 else 16
    except ValueError:
        raise SystemExit(f"--repeat-dist {spec!r}: S/K are not numeric")
    if s_exp <= 0 or catalog < 1:
        raise SystemExit(
            f"--repeat-dist {spec!r}: need S > 0 and K >= 1"
        )
    return s_exp, catalog


def build_plan(args, send_qos: bool = True) -> dict:
    """The full request plan, encoded BEFORE the clock starts: per-
    request pre-built bodies + headers, sizes, seeded QoS labels, and —
    with ``--repeat-dist`` — the payload catalog structure (which
    requests repeat an earlier payload; the cache A/B's client-side
    hit/miss split reads it).  Deterministic from --seed."""
    requests = args.requests
    rng = random.Random(args.seed)
    wire_fmt = getattr(args, "wire", "json") or "json"
    repeat_spec = getattr(args, "repeat_dist", None)
    if requests > 20000 and not repeat_spec:
        # Pre-encoding holds one body per DISTINCT payload for the whole
        # run (the encode-outside-the-window contract); with no repeat
        # catalog that is O(requests) resident bodies.  Say so rather
        # than surprise the host at six figures.
        print(
            f"note: pre-encoding {requests} distinct request bodies "
            "up front (~KBs each); use --repeat-dist zipf:S:K to bound "
            "the catalog for very large runs"
        )
    if repeat_spec:
        s_exp, catalog_n = _parse_repeat_dist(repeat_spec)
        catalog_n = min(catalog_n, requests)
        weights = [1.0 / (r ** s_exp) for r in range(1, catalog_n + 1)]
        payload_ids = rng.choices(
            range(catalog_n), weights=weights, k=requests
        )
    else:
        catalog_n = requests
        payload_ids = list(range(requests))
    # Sizes are a per-PAYLOAD property (a repeated payload is the same
    # bytes, so necessarily the same rows).
    sizes_catalog = [rng.randint(1, args.max_request) for _ in range(catalog_n)]
    mix = _parse_qos_mix(args.qos_mix) if args.qos_mix else None
    qos_labels = _draw_qos_labels(mix, requests, args.seed)
    # Encode each distinct (payload, qos) exactly once; repeats share
    # the SAME bytes object — what makes them cache hits on the wire.
    encoded: dict[tuple, tuple[bytes, dict]] = {}
    bodies: list[bytes] = []
    headers: list[dict] = []
    for i, pid in enumerate(payload_ids):
        qos = qos_labels[i] if send_qos else None
        key = (pid, qos)
        if key not in encoded:
            prng = random.Random(args.seed * 1000 + pid)
            pixels = [
                [prng.randint(0, 255) for _ in range(784)]
                for _ in range(sizes_catalog[pid])
            ]
            encoded[key] = _encode_body(
                pixels, wire_fmt, args.dtype, qos,
                log_probs=getattr(args, "json_log_probs", False),
            )
        body, hdrs = encoded[key]
        bodies.append(body)
        headers.append(hdrs)
    seen: set[int] = set()
    repeat_flags = []
    for pid in payload_ids:
        repeat_flags.append(pid in seen)
        seen.add(pid)
    return {
        "bodies": bodies,
        "headers": headers,
        "sizes": [sizes_catalog[pid] for pid in payload_ids],
        "payload_ids": payload_ids,
        "repeat_flags": repeat_flags,
        "qos_labels": qos_labels,
        "distinct": catalog_n,
        "wire": wire_fmt,
        "repeat_dist": repeat_spec,
    }


def _decode_reply(wire_fmt: str, status: int, data: bytes) -> None:
    """Client-side response decode (inside the measured window, like a
    real client): JSON parses the reply document, binary views the raw
    logits.  Each format pays its own decode cost — the honest half of
    the wire A/B."""
    if status != 200:
        return
    if wire_fmt == "binary":
        from pytorch_mnist_ddp_tpu.serving import wire

        wire.decode_response(data)
    else:
        json.loads(data)


def _parse_qos_mix(spec: str) -> dict[str, float]:
    """``interactive=0.8,batch=0.2`` -> class -> probability (must sum
    to ~1; names must be served classes — a typo'd class would 400 on
    every request of the featured rung and report a vacuously green
    A/B from empty percentile windows)."""
    from pytorch_mnist_ddp_tpu.serving.qos import QOS_CLASSES

    mix: dict[str, float] = {}
    for part in spec.split(","):
        name, _, frac = part.partition("=")
        try:
            mix[name.strip()] = float(frac)
        except ValueError:
            frac = ""
        if not frac:
            raise SystemExit(
                f"--qos-mix part {part!r} must be CLASS=FRACTION"
            )
    unknown = sorted(set(mix) - set(QOS_CLASSES))
    if unknown:
        raise SystemExit(
            f"--qos-mix names unknown class(es) {unknown}; "
            f"served classes: {list(QOS_CLASSES)}"
        )
    total = sum(mix.values())
    if not 0.999 <= total <= 1.001:
        raise SystemExit(
            f"--qos-mix fractions must sum to 1, got {total:g} ({spec!r})"
        )
    return mix


def _draw_qos_labels(
    mix: dict[str, float] | None, requests: int, seed: int
) -> list[str | None]:
    """Per-request class labels, reproducible from --seed.  A None mix
    labels every request None (no qos field is sent).  The ab-tail mode
    draws ONE label trace and reuses it for both rungs, sending the
    field only on the featured rung — so the per-class percentile
    comparison slices identical request populations."""
    if not mix:
        return [None] * requests
    rng = random.Random(seed + 7919)  # distinct stream from sizes/arrivals
    names = list(mix)
    weights = [mix[n] for n in names]
    return rng.choices(names, weights=weights, k=requests)


def run_open_loop(
    url: str,
    plan: dict,
    rate: float,
    seed: int,
    timeout_s: float,
    max_workers: int,
    dtype: str = "f32",
) -> dict:
    """Poisson arrivals at ``rate`` req/s, fired independently of
    completions, bounded by ``max_workers`` outstanding requests.

    Latency is measured from each request's SCHEDULED arrival, not from
    when an executor thread picks it up — otherwise a saturated worker
    pool silently re-closes the loop and hides client-side queueing from
    the percentiles (the coordinated-omission trap open-loop load
    generation exists to avoid).  Bodies come PRE-ENCODED from ``plan``
    (build_plan): the measured window contains transport + response
    decode only, never request serialization.
    """
    from concurrent.futures import ThreadPoolExecutor

    requests = len(plan["bodies"])
    rng = random.Random(seed)
    # Pre-draw the whole arrival schedule so the trace is reproducible
    # from --seed and the firing loop does no RNG work.
    arrivals: list[float] = []
    t = 0.0
    for _ in range(requests):
        t += rng.expovariate(rate)
        arrivals.append(t)
    bodies, headers = plan["bodies"], plan["headers"]
    qos_labels = plan["qos_labels"]
    wire_fmt = plan["wire"]

    def one(i: int, scheduled: float) -> tuple[int, float, str | None]:
        status, data = fetch_raw(
            f"{url}/predict", bodies[i], headers[i], timeout=timeout_s
        )
        _decode_reply(wire_fmt, status, data)
        return status, time.perf_counter() - scheduled, qos_labels[i]

    t_start = time.perf_counter()
    last_fired = t_start
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = []
        for i in range(requests):
            delay = t_start + arrivals[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            last_fired = time.perf_counter()
            futures.append(pool.submit(one, i, t_start + arrivals[i]))
        results = [f.result() for f in futures]
    wall = time.perf_counter() - t_start
    # achieved rate from real fire times — if the submission loop could
    # not keep up with the schedule, the report must say so rather than
    # echo the offered rate back.
    fired_span = last_fired - t_start
    return {
        "results": results,
        "wall_s": wall,
        "sizes": plan["sizes"],
        "plan": plan,
        "mode": "open-loop",
        "dtype": dtype,
        "offered_rate_rps": rate,
        "achieved_arrival_rate_rps": requests / fired_span if fired_span > 0 else 0.0,
    }


def run_load(
    url: str,
    plan: dict,
    concurrency: int,
    timeout_s: float,
    dtype: str = "f32",
) -> dict:
    """Drive the endpoint closed-loop over ``plan``'s pre-encoded
    bodies; returns raw per-request (status, latency_s, qos)."""
    requests = len(plan["bodies"])
    bodies, headers = plan["bodies"], plan["headers"]
    qos_labels = plan["qos_labels"]
    wire_fmt = plan["wire"]
    results: list[tuple[int, float, str | None]] = []
    lock = threading.Lock()
    cursor = [0]

    def worker(wid: int) -> None:
        while True:
            with lock:
                i = cursor[0]
                if i >= requests:
                    return
                cursor[0] += 1
            t0 = time.perf_counter()
            status, data = fetch_raw(
                f"{url}/predict", bodies[i], headers[i], timeout=timeout_s
            )
            _decode_reply(wire_fmt, status, data)
            elapsed = time.perf_counter() - t0
            with lock:
                results.append((status, elapsed, qos_labels[i]))

    threads = [
        threading.Thread(target=worker, args=(w,)) for w in range(concurrency)
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return {
        "results": results, "wall_s": wall, "sizes": plan["sizes"],
        "plan": plan, "mode": "closed-loop", "dtype": dtype,
    }


def summarize(raw: dict, before: dict, after: dict) -> dict:
    from pytorch_mnist_ddp_tpu.serving.metrics import percentile

    results = raw["results"]
    ok = sorted(lat for status, lat, *_ in results if status == 200)
    by_status: dict[str, int] = {}
    for status, *_ in results:
        by_status[str(status)] = by_status.get(str(status), 0) + 1
    # Per-QoS-class client-side view (the tail-latency A/B reads these):
    # latency percentiles over 200s plus shed/reject counts, per class.
    by_qos: dict[str, dict] = {}
    for status, lat, *rest in results:
        qos = rest[0] if rest else None
        if qos is None:
            continue
        entry = by_qos.setdefault(qos, {"ok": [], "statuses": {}})
        entry["statuses"][str(status)] = entry["statuses"].get(str(status), 0) + 1
        if status == 200:
            entry["ok"].append(lat)
    compiles_before = before.get("compiles")
    compiles_after = after.get("compiles")
    additional = (
        compiles_after - compiles_before
        if compiles_before is not None and compiles_after is not None
        else None
    )
    # Host-path extras, present only when the new knobs were used so
    # pre-existing report schemas stay unchanged: the wire format, the
    # repeat-workload client split (first occurrence ~ cache-miss path,
    # repeat ~ hit-eligible path), and the server's cache counters.
    plan = raw.get("plan") or {}
    extras: dict = {}
    if plan.get("wire", "json") != "json" or plan.get("repeat_dist"):
        extras["wire"] = plan.get("wire", "json")
    if plan.get("repeat_dist"):
        flags = plan["repeat_flags"]
        first = sorted(
            lat for (status, lat, *_), rep in zip(results, flags)
            if status == 200 and not rep
        )
        repeat = sorted(
            lat for (status, lat, *_), rep in zip(results, flags)
            if status == 200 and rep
        )
        extras["repeat_workload"] = {
            "repeat_dist": plan["repeat_dist"],
            "distinct_payloads": plan["distinct"],
            "repeat_fraction": sum(flags) / len(flags) if flags else 0.0,
            "first_ms": {
                "count": len(first),
                "p50": 1e3 * percentile(first, 50),
                "p99": 1e3 * percentile(first, 99),
            },
            "repeat_ms": {
                "count": len(repeat),
                "p50": 1e3 * percentile(repeat, 50),
                "p99": 1e3 * percentile(repeat, 99),
            },
        }
    if after.get("cache") is not None:
        extras["server_cache"] = after.get("cache")
    return {
        **extras,
        "mode": raw.get("mode", "closed-loop"),
        "dtype": raw.get("dtype", "f32"),
        "offered_rate_rps": raw.get("offered_rate_rps"),
        "achieved_arrival_rate_rps": raw.get("achieved_arrival_rate_rps"),
        "requests": len(results),
        "request_size_range": [min(raw["sizes"]), max(raw["sizes"])],
        "wall_s": raw["wall_s"],
        # throughput_rps keeps its historical meaning (useful 200s per
        # wall second — cross-revision BENCH comparability); goodput_rps
        # is its canonical name going forward, and answered_rps is the
        # shed-inclusive rate — under shedding load the answered/goodput
        # gap is the capacity signal a dtype A/B compares.
        "throughput_rps": len(ok) / raw["wall_s"] if raw["wall_s"] else 0.0,
        "goodput_rps": len(ok) / raw["wall_s"] if raw["wall_s"] else 0.0,
        "answered_rps": len(results) / raw["wall_s"] if raw["wall_s"] else 0.0,
        "server_dtype_latency": after.get("dtypes"),
        "status_counts": by_status,
        "rejected": by_status.get("503", 0),
        "timed_out": by_status.get("504", 0),
        "latency_ms": {
            "p50": 1e3 * percentile(ok, 50),
            "p95": 1e3 * percentile(ok, 95),
            "p99": 1e3 * percentile(ok, 99),
            "mean": 1e3 * sum(ok) / len(ok) if ok else 0.0,
        },
        "qos_latency_ms": {
            qos: {
                "requests": sum(entry["statuses"].values()),
                "ok": len(entry["ok"]),
                "rejected": entry["statuses"].get("503", 0),
                "timed_out": entry["statuses"].get("504", 0),
                "p50": 1e3 * percentile(sorted(entry["ok"]), 50),
                "p95": 1e3 * percentile(sorted(entry["ok"]), 95),
                "p99": 1e3 * percentile(sorted(entry["ok"]), 99),
            }
            for qos, entry in sorted(by_qos.items())
        } or None,
        "server_qos": after.get("qos"),
        "server_hedges": after.get("hedges"),
        "server_replicas": after.get("replicas"),
        "server_batch_occupancy_pct": after.get("batch_occupancy_pct"),
        "server_padding_waste_pct": after.get("padding_waste_pct"),
        "server_queue_depth_final": after.get("queue_depth"),
        "server_pipeline": after.get("pipeline"),
        "compiles_before": compiles_before,
        "compiles_after": compiles_after,
        "additional_compiles": additional,
        "server_metrics_before": before,
        "server_metrics_after": after,
    }


def _spin_self_serve(args, replicas: int | None):
    """Start the in-process stack (single engine, or an N-replica pool
    behind the router when ``replicas``), warmed and parity-gated.
    Returns ``(server, sink, url)``; the caller owns teardown."""
    from pytorch_mnist_ddp_tpu.obs.events import open_sink
    from pytorch_mnist_ddp_tpu.serving import InferenceEngine, ServingMetrics
    from pytorch_mnist_ddp_tpu.serving.server import make_server

    metrics = ServingMetrics()
    buckets = [int(b) for b in args.buckets.split(",")]
    dtypes = [args.dtype] if args.dtype != "f32" else None
    packed = bool(getattr(args, "packed", False))
    int8_impl = getattr(args, "int8_impl", None) or "dot"
    batcher_kwargs = dict(
        linger_ms=args.linger_ms, queue_depth=args.queue_depth,
        timeout_ms=args.timeout_ms, max_inflight=args.max_inflight,
        adaptive_linger=not args.no_adaptive_linger,
        deadline_aware=not getattr(args, "no_deadline_close", False),
        fill_wait_ms=getattr(args, "fill_wait_ms", None),
    )
    hedge = bool(
        getattr(args, "hedge", False)
        or getattr(args, "hedge_delay_ms", None) is not None
    )
    sink = open_sink(args.telemetry_dir)
    if replicas is not None:
        from pytorch_mnist_ddp_tpu.serving import EnginePool

        # Same convention as the serving CLI: 0 = one replica per
        # visible device (the EnginePool default).
        pool = EnginePool.from_seed(
            replicas=replicas or None, buckets=buckets, metrics=metrics,
            dtypes=dtypes, aot_cache=args.aot_cache,
            packed=packed, int8_impl=int8_impl,
            replica_shapes=getattr(args, "replica_shapes", None),
        )
        print(
            f"self-serve pool: warming buckets {list(pool.buckets)} x "
            f"dtypes {list(pool.dtypes)} x {pool.n_replicas} replicas"
        )
        pool.warmup(sink=sink)
        if args.dtype != "f32":
            pool.verify_parity(raise_on_failure=True)
        supervisor_kwargs = {}
        if getattr(args, "chaos", None):
            # Chaos cadence: the schedule compresses a production outage
            # into seconds, so detection/backoff must compress with it —
            # otherwise the smoke would time out waiting on defaults
            # sized for real fleets.
            supervisor_kwargs = dict(
                interval_s=0.02,
                stall_timeout_s=args.chaos_stall_timeout,
                backoff_base_s=0.1,
                backoff_max_s=1.0,
                restart_budget=8,
                seed=args.chaos_seed,
            )
        router = pool.start(
            router_policy=args.router_policy, sink=sink,
            supervisor_kwargs=supervisor_kwargs,
            hedge=hedge,
            hedge_delay_ms=getattr(args, "hedge_delay_ms", None),
            **batcher_kwargs
        )
        server = make_server(
            pool, metrics, port=0, batcher=router,
            response_cache=getattr(args, "response_cache", None),
            sink=sink,
        )
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        print(
            f"self-serve pool: {url} ({pool.n_replicas} replicas, "
            f"router policy {args.router_policy}, hedging "
            # The RESOLVED state: a 1-replica pool has no hedger even
            # when the flag asked for one.
            f"{'on' if hedge and pool.n_replicas > 1 else 'off'})"
        )
        return server, sink, url
    engine = InferenceEngine.from_seed(
        buckets=buckets, metrics=metrics, dtypes=dtypes,
        aot_cache=args.aot_cache,
        packed=packed, int8_impl=int8_impl,
    )
    print(
        f"self-serve: warming buckets {list(engine.buckets)} x dtypes "
        f"{list(engine.dtypes)}"
    )
    engine.warmup()
    if args.dtype != "f32":
        # The variant must clear its parity gate before a single
        # request routes to it (the refusal contract): fail the
        # A/B loudly rather than measure an unverified path.
        gate = engine.verify_parity(raise_on_failure=True)[args.dtype]
        print(
            f"parity gate [{args.dtype}]: PASS "
            f"(max|dlogit| {gate['max_abs_logit_diff']:.2e} <= "
            f"{gate['tolerance']:g}, argmax identical)"
        )
    server = make_server(
        engine, metrics, port=0, sink=sink,
        response_cache=getattr(args, "response_cache", None),
        **batcher_kwargs,
    )
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    print(
        f"self-serve: {url} (in-flight window {args.max_inflight}, "
        f"adaptive linger {'off' if args.no_adaptive_linger else 'on'})"
    )
    return server, sink, url


def _teardown_self_serve(server, sink) -> None:
    if server is not None:
        server.shutdown()
        # Pool mode: stop the supervisor BEFORE the router drain (a
        # restart racing the teardown would attach a fresh batcher to a
        # router tearing its replicas down); EnginePool.stop owns that
        # ordering.  Single engine: plain batcher drain.
        if getattr(server.engine, "supervisor", None) is not None:
            server.engine.stop(drain=True)
        else:
            server.batcher.stop(drain=True)
        server.server_close()
    if sink is not None:
        sink.close()


def _drive(args, url: str, send_qos: bool = True) -> dict:
    """Fire the configured workload (open or closed loop) at ``url``.

    ``send_qos=False`` keeps the per-request class LABELS (for the
    report's per-class slices) but omits the payload field — the
    baseline rung of the tail A/B.  The WHOLE plan (sizes, labels,
    repeat structure, encoded bodies) is built here, before the clock."""
    plan = build_plan(args, send_qos=send_qos)
    wire_note = f", wire {plan['wire']}" if plan["wire"] != "json" else ""
    repeat_note = (
        f", repeat-dist {plan['repeat_dist']} ({plan['distinct']} distinct)"
        if plan["repeat_dist"] else ""
    )
    if args.open_loop:
        print(
            f"driving {args.requests} open-loop Poisson arrivals of "
            f"1..{args.max_request} samples at {args.rate:.0f} req/s"
            f"{wire_note}{repeat_note}"
            + (f" (qos mix {args.qos_mix}"
               + (", field sent" if send_qos else ", labels only") + ")"
               if args.qos_mix else "")
        )
        return run_open_loop(
            url, plan, args.rate, args.seed, args.timeout_s,
            max_workers=args.concurrency,
            dtype=args.dtype,
        )
    print(
        f"driving {args.requests} requests of 1..{args.max_request} "
        f"samples at concurrency {args.concurrency}{wire_note}{repeat_note}"
    )
    return run_load(
        url, plan, args.concurrency, args.timeout_s, dtype=args.dtype,
    )


def _await_recovery(server, url: str, timeout_s: float) -> bool:
    """Post-chaos settle: poll until every replica is healthy (state
    active/drained/ejected and circuit not open), firing small probe
    requests so half-open circuits get the trial traffic they need to
    close — an idle pool would otherwise sit half-open forever, and the
    final prom dump would report a recovery still in flight."""
    router = server.batcher
    deadline = time.perf_counter() + timeout_s
    probe = {"instances": [[0] * 784], "normalized": True}
    while time.perf_counter() < deadline:
        stats = router.replica_stats()
        unsettled = [
            name for name, s in stats.items()
            if s["state"] in ("quarantined", "restarting")
            # Ejection is a SETTLED terminal state; its breaker is
            # force-opened permanently, so the circuit check must not
            # hold an exhausted-restart-budget replica "in flight"
            # until the wait expires.
            or (s["state"] != "ejected"
                and s.get("circuit") in ("open", "half-open"))
        ]
        if not unsettled:
            return True
        fetch_json(f"{url}/predict", probe, timeout=5.0)
        time.sleep(0.05)
    return False


def run_chaos(args, server, sink, url) -> tuple[dict, dict, dict, dict]:
    """Drive the workload under an installed fault schedule; returns
    (raw results, before, after, chaos report section).  The injector's
    virtual clock starts when the workload does, so ``at=`` clauses are
    relative to first arrival — 'kill replica 2 at t=5s' means five
    seconds into the RUN, not into warmup."""
    from pytorch_mnist_ddp_tpu.serving import faults

    injector = faults.install(
        faults.FaultInjector(args.chaos, seed=args.chaos_seed)
    )
    print(f"chaos: armed {len(injector.specs)} clause(s): {args.chaos}")
    _status, before = fetch_json(f"{url}/metrics")
    injector.start()
    try:
        raw = _drive(args, url)
    finally:
        faults.uninstall()
    recovered = _await_recovery(server, url, args.chaos_recovery_wait)
    _status, after = fetch_json(f"{url}/metrics")
    pool = server.engine
    router = server.batcher
    supervisor = getattr(pool, "supervisor", None)
    sup_stats = supervisor.stats() if supervisor is not None else {}
    per_replica = sup_stats.get("replicas", {})
    chaos = {
        "spec": args.chaos,
        "seed": args.chaos_seed,
        "fired": injector.fired_counts(),
        # Clauses that never fired, split by determinism: a p=-triggered
        # clause can legitimately miss on a short run, but a count/after/
        # at clause that fired zero times means the schedule never
        # exercised what it claims to prove — e.g. warmup/aot_load sites,
        # which the self-serve pool has already passed by the time the
        # injector is armed (drive those from tests/test_faults.py).
        "unfired": [s.source for s in injector.specs
                    if s.fired == 0 and s.p >= 1.0],
        "unfired_probabilistic": [s.source for s in injector.specs
                                  if s.fired == 0 and s.p < 1.0],
        "recovered": recovered,
        "restarts": {
            name: per_replica.get(name, {}).get("restarts", 0)
            for name in pool.replica_names
        },
        "mean_recovery_s": sup_stats.get("mean_recovery_s"),
        "replica_states": {
            name: s["state"] for name, s in router.replica_stats().items()
        },
        "circuits": {
            name: s.get("circuit")
            for name, s in router.replica_stats().items()
        },
        "retries": after.get("retries"),
    }
    return raw, before, after, chaos


def run_replica_sweep(args) -> int:
    """The scale-out A/B: the SAME workload against self-serve pools of
    increasing replica counts, reporting goodput and p99 per rung plus
    scaling efficiency (goodput_N / (N x goodput_1)) —
    ``BENCH_serving_scaleout.json``."""
    counts = [int(c) for c in args.replicas_sweep.split(",")]
    if any(c < 1 for c in counts):
        raise SystemExit("--replicas-sweep counts must be >= 1")
    rows = []
    rc = 0
    for i, n in enumerate(counts):
        server, sink, url = _spin_self_serve(args, replicas=n)
        try:
            _status, before = fetch_json(f"{url}/metrics")
            raw = _drive(args, url)
            _status, after = fetch_json(f"{url}/metrics")
            if args.prom_dump and i == len(counts) - 1:
                with open(args.prom_dump, "w") as f:
                    f.write(fetch_text(f"{url}/metrics?format=prom"))
                print(f"prometheus exposition ({n} replicas): {args.prom_dump}")
        finally:
            _teardown_self_serve(server, sink)
        report = summarize(raw, before, after)
        extra = report["additional_compiles"]
        if extra and not args.no_check_compiles:
            print(f"RETRACE at {n} replicas: {extra} additional compile(s)")
            rc = 1
        rows.append({
            "replicas": n,
            "goodput_rps": report["goodput_rps"],
            "answered_rps": report["answered_rps"],
            "p50_ms": report["latency_ms"]["p50"],
            "p99_ms": report["latency_ms"]["p99"],
            "rejected": report["rejected"],
            "timed_out": report["timed_out"],
            "additional_compiles": extra,
            "router_policy": args.router_policy,
        })
    # Both ratios promise a 1-replica baseline; a sweep that starts at
    # some other rung (e.g. --replicas-sweep 2,4) has no such baseline,
    # so they stay None rather than quietly rebasing.
    base = rows[0]["goodput_rps"] if rows[0]["replicas"] == 1 else None
    for row in rows:
        row["speedup_vs_1"] = (
            row["goodput_rps"] / base if base else None
        )
        row["scaling_efficiency"] = (
            row["goodput_rps"] / (row["replicas"] * base)
            if base else None
        )
    sweep_report = {
        "mode": "open-loop" if args.open_loop else "closed-loop",
        "router_policy": args.router_policy,
        "requests": args.requests,
        "max_request": args.max_request,
        "buckets": [int(b) for b in args.buckets.split(",")],
        "offered_rate_rps": args.rate if args.open_loop else None,
        "sweep": rows,
    }
    with open(args.scaleout_report, "w") as f:
        json.dump(sweep_report, f, indent=2)
    print(f"scale-out report: {args.scaleout_report}")
    for row in rows:
        eff = row["scaling_efficiency"]
        print(
            f"  {row['replicas']} replica(s): "
            f"{row['goodput_rps']:.1f} goodput req/s, "
            f"p99 {row['p99_ms']:.2f} ms, {row['rejected']} rejected"
            + (f", efficiency {eff:.2f}" if eff is not None else "")
        )
    return rc


def _spin_fleet(args, n: int, autoscale: bool = False):
    """Bring up an n-backend FLEET behind an in-process front server
    (docs/SERVING.md fleet section): real serving subprocesses sharing
    one AOT cache by default, or — with ``--fleet-fake`` — in-process
    fake backends with serial capacity (the structural mode for the
    host-bound CI box).  Returns ``(server, fleet, fakes, sink, url)``;
    the caller owns teardown."""
    import tempfile

    from pytorch_mnist_ddp_tpu.obs.events import EventSink, NullSink
    from pytorch_mnist_ddp_tpu.serving.fleet import (
        Fleet,
        fake_backend_spawner,
        make_fleet_server,
        subprocess_backend_spawner,
    )
    from pytorch_mnist_ddp_tpu.serving.metrics import ServingMetrics

    sink = (
        EventSink(args.telemetry_dir, filename="events-fleet.jsonl")
        if args.telemetry_dir else NullSink()
    )
    fakes: dict = {}
    hb_dir = tempfile.mkdtemp(prefix="fleet-hb-")
    if args.fleet_fake:
        spawn = fake_backend_spawner(
            service_s=args.fleet_service_ms / 1e3,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            heartbeat_dir=hb_dir,
            registry=fakes,
        )
        # Compressed supervision, like --chaos: the kill round injects
        # an outage measured in milliseconds, so detection and backoff
        # must compress with it.
        supervisor_kwargs = dict(
            interval_s=0.05, probe_timeout_s=0.5, probe_failures=3,
            backoff_base_s=0.05, backoff_max_s=0.5, grace_s=2.0,
            heartbeat_timeout_s=2.0, ready_timeout_s=30.0,
        )
    else:
        aot = args.aot_cache or tempfile.mkdtemp(prefix="fleet-aot-")
        spawn = subprocess_backend_spawner(
            [
                "--buckets", args.buckets,
                "--timeout-ms", str(args.timeout_ms),
                "--queue-depth", str(args.queue_depth),
                "--max-inflight", str(args.max_inflight),
                "--aot-cache", aot,
            ],
            base_port=args.fleet_base_port,
            heartbeat_dir=hb_dir,
            log_dir=args.telemetry_dir,
        )
        supervisor_kwargs = dict(
            interval_s=0.2, probe_timeout_s=1.0, probe_failures=3,
            backoff_base_s=0.2, backoff_max_s=1.0, grace_s=5.0,
            heartbeat_timeout_s=10.0, ready_timeout_s=180.0,
        )
    fleet = Fleet(
        spawn, policy=args.router_policy, metrics=ServingMetrics(),
        sink=sink, poll_s=0.1,
        default_timeout_s=args.timeout_ms / 1e3 + 2.0,
    )
    print(
        f"fleet: bringing up {n} "
        f"{'fake' if args.fleet_fake else 'real'} backend(s) "
        f"(policy {args.router_policy})"
    )
    fleet.start(
        n, wait_ready_s=300.0, supervise=True,
        supervisor_kwargs=supervisor_kwargs,
        autoscale=autoscale,
        # Compressed control loop, matched to the fakes' compressed
        # service times: high water a few queued requests per backend,
        # sub-second sustain window, everything interactive-speed.
        autoscaler_kwargs=dict(
            high_water=3.0, low_water=0.5, window_s=0.3,
            cooldown_s=1.0, min_backends=n, max_backends=n + 1,
            interval_s=0.05,
        ) if autoscale else None,
    )
    server = make_fleet_server(fleet, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    print(f"fleet front: {url} ({n} backends ready)")
    return server, fleet, fakes, sink, url


def _teardown_fleet(server, fleet, sink) -> None:
    if server is not None:
        server.shutdown()
        server.server_close()
    if fleet is not None:
        fleet.stop()
    if sink is not None:
        sink.close()


def _fleet_kill_round(args, rows_max: int) -> tuple[dict, int]:
    """Recovery-under-kill: drive the open-loop trace against the
    biggest fleet and SIGKILL one backend mid-drive.  The front must
    absorb it: zero lost responses, zero client transport errors, 503
    rate within the bound, the backend REPLACED (restart counter >= 1,
    everything active again) and the replacement serving with zero
    post-warmup compiles (shared-AOT warm start).  Returns the report
    section and an exit code contribution."""
    import signal as _signal

    from pytorch_mnist_ddp_tpu.liveness import signal_process_group

    rc = 0
    server, fleet, fakes, sink, url = _spin_fleet(args, rows_max)
    victim = fleet.backends_snapshot()[-1].name
    kill_at_s = 0.4 * args.requests / args.rate

    def _kill():
        print(f"fleet: KILLING backend {victim} (SIGKILL, mid-drive)")
        if args.fleet_fake:
            fakes[victim].kill()
        else:
            signal_process_group(
                fleet.backend(victim).proc, _signal.SIGKILL
            )

    timer = threading.Timer(kill_at_s, _kill)
    timer.start()
    try:
        _status, before = fetch_json(f"{url}/metrics")
        raw = _drive(args, url)
        timer.join()
        # Post-drive settle: the replacement must be serving again
        # within the recovery window.
        deadline = time.perf_counter() + args.fleet_recovery_wait
        replaced = False
        while time.perf_counter() < deadline:
            _status, snap = fetch_json(f"{url}/metrics")
            states = {
                name: b["state"]
                for name, b in (snap.get("backends") or {}).items()
                if b["state"] != "retired"
            }
            sup = (snap.get("fleet") or {}).get("supervisor") or {}
            if (states and all(s == "active" for s in states.values())
                    and (sup.get("restarts_total") or 0) >= 1):
                replaced = True
                break
            time.sleep(0.1)
        _status, after = fetch_json(f"{url}/metrics")
        if args.prom_dump:
            with open(args.prom_dump, "w") as f:
                f.write(fetch_text(f"{url}/metrics?format=prom"))
            print(f"prometheus exposition (kill round): {args.prom_dump}")
    finally:
        timer.cancel()
        _teardown_fleet(server, fleet, sink)
    results = raw["results"]
    lost = args.requests - len(results)
    transport = sum(1 for status, *_ in results if status == 0)
    rejected = sum(1 for status, *_ in results if status == 503)
    rate_503 = rejected / len(results) if results else 0.0
    replacement_compiles = (
        (after.get("backends") or {}).get(victim, {}).get("compiles")
    )
    sup = (after.get("fleet") or {}).get("supervisor") or {}
    recovery = {
        "backends": rows_max,
        "killed": victim,
        "kill_at_s": kill_at_s,
        "lost": lost,
        "transport_errors": transport,
        "rejected": rejected,
        "rejected_rate": rate_503,
        "replaced": replaced,
        "restarts_total": sup.get("restarts_total"),
        "mean_replacement_s": sup.get("mean_recovery_s"),
        "replacement_compiles": replacement_compiles,
        "goodput_rps": (
            sum(1 for status, *_ in results if status == 200) / raw["wall_s"]
            if raw["wall_s"] else 0.0
        ),
    }
    if lost or transport:
        print(
            f"FLEET-KILL FAIL: {lost} lost response(s), "
            f"{transport} client transport error(s) — the front must "
            "absorb a backend kill"
        )
        rc = 1
    if rate_503 > args.fleet_max_503_rate:
        print(
            f"FLEET-KILL FAIL: 503 rate {rate_503:.1%} exceeds the "
            f"--fleet-max-503-rate bound {args.fleet_max_503_rate:.1%}"
        )
        rc = 1
    if not replaced:
        print(
            f"FLEET-KILL FAIL: {victim} not replaced within "
            f"{args.fleet_recovery_wait:.0f}s"
        )
        rc = 1
    if replacement_compiles:
        print(
            f"FLEET-KILL FAIL: replacement {victim} reports "
            f"{replacement_compiles} compile(s) — a warm start off the "
            "shared AOT cache must deserialize, not trace"
        )
        rc = 1
    if rc == 0:
        print(
            f"fleet kill round: {victim} killed at {kill_at_s:.1f}s, "
            f"replaced in {recovery['mean_replacement_s'] or 0.0:.2f}s, "
            f"0 lost, 503 rate {rate_503:.1%}, replacement compiles "
            f"{replacement_compiles}"
        )
    return recovery, rc


def _fleet_autoscale_round(args) -> tuple[dict, int]:
    """The elasticity drill (--fleet-fake only — real backends on a
    2-core box cannot be saturated honestly): start ONE backend with the
    autoscaler on, drive a sustained over-capacity open-loop trace so
    the smoothed backlog breaches the high-water mark and the fleet
    scales 1 -> 2, then go idle so it drains the newest backend back
    down (drain -> settle -> kill).  Fails on any lost response, any
    non-200 outcome, a missing scale-up, or a missing drain-down."""
    rc = 0
    server, fleet, _fakes, sink, url = _spin_fleet(args, 1, autoscale=True)
    try:
        _status, before = fetch_json(f"{url}/metrics")
        raw = _drive(args, url)
        # Idle: the backlog signal decays below the low-water mark and
        # the newest backend drains back out.
        deadline = time.perf_counter() + args.fleet_recovery_wait
        drained = False
        while time.perf_counter() < deadline:
            _status, snap = fetch_json(f"{url}/metrics")
            states = [
                b["state"]
                for b in (snap.get("backends") or {}).values()
            ]
            if states.count("active") == 1 and "retired" in states:
                drained = True
                break
            time.sleep(0.1)
        _status, after = fetch_json(f"{url}/metrics")
    finally:
        _teardown_fleet(server, fleet, sink)
    results = raw["results"]
    lost = args.requests - len(results)
    non_200 = sum(1 for status, *_ in results if status != 200)
    scaled_up = any(
        b["state"] in ("active", "retired")
        for name, b in (after.get("backends") or {}).items()
        if name != "b0"
    )
    section = {
        "offered_rate_rps": args.rate,
        "requests": args.requests,
        "lost": lost,
        "non_200": non_200,
        "scaled_up": scaled_up,
        "drained_back": drained,
        "final_backends": {
            name: b["state"]
            for name, b in (after.get("backends") or {}).items()
        },
    }
    if lost or non_200:
        print(
            f"FLEET-AUTOSCALE FAIL: {lost} lost, {non_200} non-200 "
            "outcome(s) — scaling must lose nothing"
        )
        rc = 1
    if not scaled_up:
        print("FLEET-AUTOSCALE FAIL: never scaled 1 -> 2 under sustained "
              "over-capacity load")
        rc = 1
    if not drained:
        print("FLEET-AUTOSCALE FAIL: never drained back down at idle "
              f"within {args.fleet_recovery_wait:.0f}s")
        rc = 1
    if rc == 0:
        print(
            f"fleet autoscale round: scaled 1 -> 2 under load, drained "
            f"back at idle, 0 lost ({section['final_backends']})"
        )
    return section, rc


def run_fleet_sweep(args) -> int:
    """The fleet scale-out A/B (docs/SERVING.md): the SAME open-loop
    trace against fleets of increasing backend count → goodput / p99 /
    scaling efficiency per rung, then the recovery-under-kill round —
    all recorded in ``--fleet-report`` (BENCH_fleet.json).

    On the 2-core CI box the REAL sweep is host-bound (the PR-4/7
    caveat: N jax processes share two cores, so goodput flattens);
    ``--fleet-fake`` swaps in serial-capacity fake backends over real
    sockets, which pins the routing/scaling structure (4 backends beat
    1 by >2.5x wall) without the host bound — the same split as the
    replica sweep's fake-device pin."""
    if not args.open_loop:
        raise SystemExit(
            "--fleet-sweep is an open-loop drill (the kill round's "
            "arrival schedule must not re-close around the outage); add "
            "--open-loop --rate R"
        )
    counts = [int(c) for c in args.fleet_sweep.split(",")]
    if any(c < 1 for c in counts):
        raise SystemExit("--fleet-sweep counts must be >= 1")
    rows = []
    rc = 0
    for n in counts:
        server, fleet, _fakes, sink, url = _spin_fleet(args, n)
        try:
            _status, before = fetch_json(f"{url}/metrics")
            raw = _drive(args, url)
            _status, after = fetch_json(f"{url}/metrics")
        finally:
            _teardown_fleet(server, fleet, sink)
        report = summarize(raw, before, after)
        extra = report["additional_compiles"]
        if extra and extra > 0 and not args.no_check_compiles:
            print(f"RETRACE at {n} backends: {extra} additional compile(s)")
            rc = 1
        rows.append({
            "backends": n,
            "goodput_rps": report["goodput_rps"],
            "answered_rps": report["answered_rps"],
            "wall_s": raw["wall_s"],
            "p50_ms": report["latency_ms"]["p50"],
            "p99_ms": report["latency_ms"]["p99"],
            "rejected": report["rejected"],
            "timed_out": report["timed_out"],
            "additional_compiles": extra,
        })
    base = rows[0] if rows[0]["backends"] == 1 else None
    for row in rows:
        row["speedup_vs_1"] = (
            row["goodput_rps"] / base["goodput_rps"]
            if base and base["goodput_rps"] else None
        )
        row["scaling_efficiency"] = (
            row["goodput_rps"] / (row["backends"] * base["goodput_rps"])
            if base and base["goodput_rps"] else None
        )
    recovery = None
    if not args.no_fleet_kill:
        recovery, kill_rc = _fleet_kill_round(args, max(counts))
        rc = rc or kill_rc
    autoscale_round = None
    if args.fleet_fake and not args.no_fleet_autoscale:
        autoscale_round, scale_rc = _fleet_autoscale_round(args)
        rc = rc or scale_rc
    fleet_report = {
        "mode": "fleet-sweep",
        "backend_kind": "fake" if args.fleet_fake else "process",
        "host_bound_caveat": (
            None if args.fleet_fake else
            "real backends share this host's cores; on a small CI box "
            "goodput flattens at the host bound (docs/SERVING.md) — the "
            "scaling structure is pinned by the --fleet-fake rung and "
            "tests/test_fleet.py"
        ),
        "router_policy": args.router_policy,
        "requests": args.requests,
        "offered_rate_rps": args.rate,
        "max_request": args.max_request,
        "buckets": [int(b) for b in args.buckets.split(",")],
        "fake_service_ms": (
            args.fleet_service_ms if args.fleet_fake else None
        ),
        "sweep": rows,
        "recovery_under_kill": recovery,
        "autoscale_round": autoscale_round,
    }
    with open(args.fleet_report, "w") as f:
        json.dump(fleet_report, f, indent=2)
    print(f"fleet report: {args.fleet_report}")
    for row in rows:
        eff = row["scaling_efficiency"]
        print(
            f"  {row['backends']} backend(s): "
            f"{row['goodput_rps']:.1f} goodput req/s, wall "
            f"{row['wall_s']:.2f}s, p99 {row['p99_ms']:.2f} ms, "
            f"{row['rejected']} rejected"
            + (f", efficiency {eff:.2f}" if eff is not None else "")
        )
    return rc


def run_ab_tail(args) -> int:
    """The tail-latency A/B (docs/SERVING.md QoS section): the SAME
    open-loop Poisson trace — identical arrivals, sizes, and per-request
    class labels — against two self-serve pools:

    - **baseline**: feature off.  No ``qos`` field is sent (every
      request is default-class FIFO), batch close honors the global
      linger, no hedging.
    - **tail**: feature on.  The class labels ride the payload, batches
      close deadline-aware, and stragglers hedge to a second replica
      (``--hedge-delay-ms``, or the per-class p99 digest).

    Per-class p50/p95/p99 deltas land in ``--tail-report``
    (BENCH_tail.json).  The run FAILS on any lost response, any
    transport error, any duplicated client-visible outcome (the server's
    completed counter moving past the client's request count — the
    hedge-double-count check), or any post-warmup compile.
    """
    if not args.open_loop:
        raise SystemExit(
            "--ab-tail is an open-loop A/B (the tail is an arrival-rate "
            "phenomenon); add --open-loop --rate R"
        )
    if args.max_request > max(int(b) for b in args.buckets.split(",")):
        # A request bigger than the top bucket shards into N chunks and
        # the server counts each chunk's completion — the
        # completed-vs-(200s+504s) duplicate check below would read the
        # fan-out as phantom hedge double-counts and FAIL a correct run.
        raise SystemExit(
            "--ab-tail needs --max-request <= the top bucket (sharded "
            "chunk fan-out breaks the per-request completed-count "
            "accounting the duplicate check relies on)"
        )
    if args.replicas is None:
        args.replicas = 2  # hedging needs a second replica
    elif args.replicas < 2:
        # A 1-replica pool has no hedger (Router silently skips it) —
        # the "feature-on" rung would be unhedged while BENCH_tail.json
        # labels it hedged.  0 (one per visible device) is also refused:
        # it can resolve to 1 on a single-device host.
        raise SystemExit(
            "--ab-tail needs --replicas >= 2: the feature-on rung hedges, "
            "and a lone replica has no second replica to hedge onto"
        )
    if not args.qos_mix:
        args.qos_mix = "interactive=0.8,batch=0.2"
    rungs = []
    rc = 0
    for label, send_qos, overrides in (
        ("baseline", False, dict(
            no_deadline_close=True, hedge=False, hedge_delay_ms=None)),
        ("tail", True, dict(
            no_deadline_close=False, hedge=True,
            hedge_delay_ms=args.hedge_delay_ms)),
    ):
        rung_args = argparse.Namespace(**{**vars(args), **overrides})
        print(f"--- ab-tail rung: {label} ---")
        server, sink, url = _spin_self_serve(
            rung_args, replicas=rung_args.replicas
        )
        try:
            _status, before = fetch_json(f"{url}/metrics")
            raw = _drive(rung_args, url, send_qos=send_qos)
            _status, after = fetch_json(f"{url}/metrics")
            if args.prom_dump and label == "tail":
                with open(args.prom_dump, "w") as f:
                    f.write(fetch_text(f"{url}/metrics?format=prom"))
                print(f"prometheus exposition (tail rung): {args.prom_dump}")
        finally:
            _teardown_self_serve(server, sink)
        report = summarize(raw, before, after)
        results = raw["results"]
        lost = args.requests - len(results)
        transport = sum(1 for status, *_ in results if status == 0)
        completed_delta = (
            after["requests"]["completed"] - before["requests"]["completed"]
        )
        # Exactly-one-outcome check: every server-side completion must
        # correspond to a client 200, or to a client 504 whose late
        # result landed after the client stopped waiting.  Anything
        # beyond that is a duplicated outcome (a hedge double-count).
        # Bounding by ok+504 — not by args.requests — keeps the check
        # honest under load: sheds and rejections must not open
        # headroom that masks real duplicates.
        ok_count = sum(1 for status, *_ in results if status == 200)
        client_504 = sum(1 for status, *_ in results if status == 504)
        duplicates = max(0, completed_delta - ok_count - client_504)
        if lost or transport or duplicates:
            print(
                f"AB-TAIL FAIL [{label}]: {lost} lost response(s), "
                f"{transport} transport error(s), {duplicates} "
                "duplicated client-visible outcome(s)"
            )
            rc = 1
        extra = report["additional_compiles"]
        if extra and not args.no_check_compiles:
            print(f"AB-TAIL FAIL [{label}]: {extra} additional compile(s)")
            rc = 1
        rungs.append({
            "label": label,
            "qos_sent": send_qos,
            "lost": lost,
            "transport_errors": transport,
            "completed_delta": completed_delta,
            "duplicates": duplicates,
            "goodput_rps": report["goodput_rps"],
            "latency_ms": report["latency_ms"],
            "qos_latency_ms": report["qos_latency_ms"],
            "server_qos": report["server_qos"],
            "server_hedges": report["server_hedges"],
            "rejected": report["rejected"],
            "timed_out": report["timed_out"],
            "additional_compiles": extra,
        })
    base, tail = rungs
    deltas: dict[str, dict] = {}
    for qos in sorted(set(base["qos_latency_ms"] or {})
                      & set(tail["qos_latency_ms"] or {})):
        b = base["qos_latency_ms"][qos]
        t = tail["qos_latency_ms"][qos]
        deltas[qos] = {
            key: {
                "baseline_ms": b[key],
                "tail_ms": t[key],
                "delta_ms": t[key] - b[key],
                "delta_pct": (
                    100.0 * (t[key] - b[key]) / b[key] if b[key] else None
                ),
            }
            for key in ("p50", "p95", "p99")
        }
    goodput_ratio = (
        tail["goodput_rps"] / base["goodput_rps"]
        if base["goodput_rps"] else None
    )
    ab_report = {
        "mode": "ab-tail",
        "offered_rate_rps": args.rate,
        "requests": args.requests,
        "replicas": args.replicas,
        "qos_mix": args.qos_mix,
        "hedge_delay_ms": args.hedge_delay_ms,
        "buckets": [int(b) for b in args.buckets.split(",")],
        "rungs": rungs,
        "deltas": deltas,
        "goodput_ratio_tail_vs_baseline": goodput_ratio,
    }
    with open(args.tail_report, "w") as f:
        json.dump(ab_report, f, indent=2)
    print(f"tail A/B report: {args.tail_report}")
    for qos, d in deltas.items():
        print(
            f"  {qos}: p50 {d['p50']['baseline_ms']:.1f} -> "
            f"{d['p50']['tail_ms']:.1f} ms, p99 "
            f"{d['p99']['baseline_ms']:.1f} -> {d['p99']['tail_ms']:.1f} ms "
            f"({d['p99']['delta_pct']:+.1f}%)"
            if d["p99"]["delta_pct"] is not None else f"  {qos}: (no data)"
        )
    hedges = tail["server_hedges"] or {}
    placed = hedges.get("won", 0) + hedges.get("lost", 0)
    print(
        "  goodput ratio "
        + (f"{goodput_ratio:.3f}" if goodput_ratio is not None
           else "n/a (baseline completed zero requests)")
        + f", hedges {hedges.get('won', 0)} won / "
        f"{hedges.get('lost', 0)} lost / "
        f"{hedges.get('cancelled', 0)} cancelled"
        + (f" (win rate {hedges.get('won', 0) / placed:.1%})" if placed else "")
    )
    return rc


def _rung_verdict(args, raw, before, after, report, label) -> tuple[dict, int]:
    """Shared per-rung accounting for the hostpath rounds: loss,
    transport errors, duplicated outcomes (server completions beyond
    client 200s+504s — cache hits/coalesces complete nothing server-side
    so they only SHRINK the delta), and the retrace check."""
    rc = 0
    results = raw["results"]
    lost = args.requests - len(results)
    transport = sum(1 for status, *_ in results if status == 0)
    ok = sum(1 for status, *_ in results if status == 200)
    c504 = sum(1 for status, *_ in results if status == 504)
    completed_delta = (
        after["requests"]["completed"] - before["requests"]["completed"]
    )
    duplicates = max(0, completed_delta - ok - c504)
    extra = report["additional_compiles"]
    if lost or transport or duplicates:
        print(
            f"HOSTPATH FAIL [{label}]: {lost} lost response(s), "
            f"{transport} transport error(s), {duplicates} duplicated "
            "client-visible outcome(s)"
        )
        rc = 1
    if extra and not args.no_check_compiles:
        print(f"HOSTPATH FAIL [{label}]: {extra} additional compile(s)")
        rc = 1
    row = {
        "label": label,
        "requests": len(results),
        "lost": lost,
        "transport_errors": transport,
        "duplicates": duplicates,
        "goodput_rps": report["goodput_rps"],
        "answered_rps": report["answered_rps"],
        "latency_ms": report["latency_ms"],
        "rejected": report["rejected"],
        "timed_out": report["timed_out"],
        "additional_compiles": extra,
        "server_wire": (after.get("wire") or {}),
    }
    return row, rc


def run_hostpath(args) -> int:
    """The host hot-path A/B (docs/SERVING.md; BENCH_hostpath.json):

    1. **wire A/B** — the SAME open-loop trace (arrivals, sizes,
       payload pixels) against a fresh self-serve stack twice, once per
       wire format at equal offered rate.  Binary's win is pure host
       work deleted: no per-pixel text parse server-side, no JSON
       document client-side.
    2. **cache round** — a zipf-repeated payload workload
       (``--repeat-dist``, default ``zipf:1.1:16``) on the binary wire
       with the response cache on (``--response-cache``, default 64):
       server hit/miss/coalesced counters plus the client-side
       first-occurrence (miss path) vs repeat (hit path) percentile
       split.

    Every round fails on lost responses, transport errors, duplicated
    outcomes, or post-warmup compiles; the cache round additionally
    fails on a zero hit count or a hit-path p99 that is not under the
    miss-path p99.
    """
    if not args.open_loop:
        raise SystemExit(
            "--hostpath-ab is an open-loop A/B (the win is host work "
            "deleted at a FIXED offered rate; a closed loop would "
            "re-close around the faster path); add --open-loop --rate R"
        )
    rc = 0
    rungs: dict[str, dict] = {}
    for wire_fmt in ("json", "binary"):
        rung_args = argparse.Namespace(**{
            **vars(args),
            "wire": wire_fmt, "repeat_dist": None, "response_cache": None,
            # Equal information per response: the binary wire always
            # returns the full logits, so the JSON rung asks for
            # log_probs rather than the (smaller) predictions-only
            # answer.
            "json_log_probs": True,
        })
        print(f"--- hostpath rung: wire {wire_fmt} ---")
        server, sink, url = _spin_self_serve(rung_args, replicas=args.replicas)
        try:
            _status, before = fetch_json(f"{url}/metrics")
            raw = _drive(rung_args, url)
            _status, after = fetch_json(f"{url}/metrics")
        finally:
            _teardown_self_serve(server, sink)
        report = summarize(raw, before, after)
        row, rung_rc = _rung_verdict(args, raw, before, after, report, wire_fmt)
        rc = rc or rung_rc
        rungs[wire_fmt] = row
    goodput_ratio = (
        rungs["binary"]["goodput_rps"] / rungs["json"]["goodput_rps"]
        if rungs["json"]["goodput_rps"] else None
    )
    p50_ratio = (
        rungs["binary"]["latency_ms"]["p50"] / rungs["json"]["latency_ms"]["p50"]
        if rungs["json"]["latency_ms"]["p50"] else None
    )
    # The cache round: binary wire (the taught fast path), seeded zipf
    # repeats, cache on at both tiers the self-serve stack has (the
    # admission point; there is no fleet front here).
    cache_args = argparse.Namespace(**{
        **vars(args),
        "wire": "binary",
        "repeat_dist": args.repeat_dist or "zipf:1.1:16",
        "response_cache": args.response_cache or 64,
        "rate": args.cache_rate or args.rate,
    })
    print(
        f"--- hostpath rung: response cache "
        f"({cache_args.repeat_dist}, {cache_args.response_cache} entries, "
        f"{cache_args.rate:.0f} req/s) ---"
    )
    server, sink, url = _spin_self_serve(cache_args, replicas=args.replicas)
    try:
        _status, before = fetch_json(f"{url}/metrics")
        raw = _drive(cache_args, url)
        _status, after = fetch_json(f"{url}/metrics")
        if args.prom_dump:
            with open(args.prom_dump, "w") as f:
                f.write(fetch_text(f"{url}/metrics?format=prom"))
            print(f"prometheus exposition (cache round): {args.prom_dump}")
    finally:
        _teardown_self_serve(server, sink)
    report = summarize(raw, before, after)
    row, rung_rc = _rung_verdict(args, raw, before, after, report, "cache")
    rc = rc or rung_rc
    server_cache = report.get("server_cache") or {}
    split = report.get("repeat_workload") or {}
    hits = server_cache.get("hit", 0)
    first_p99 = (split.get("first_ms") or {}).get("p99")
    repeat_p99 = (split.get("repeat_ms") or {}).get("p99")
    if not hits:
        print("HOSTPATH FAIL [cache]: zero cache hits under a zipf "
              "repeat workload — the cache tier did nothing")
        rc = 1
    elif first_p99 and repeat_p99 is not None and repeat_p99 >= first_p99:
        print(
            f"HOSTPATH FAIL [cache]: hit-path p99 {repeat_p99:.2f} ms is "
            f"not under miss-path p99 {first_p99:.2f} ms"
        )
        rc = 1
    cache_round = {
        **row,
        "offered_rate_rps": cache_args.rate,
        "repeat_dist": cache_args.repeat_dist,
        "response_cache": cache_args.response_cache,
        "server_cache": server_cache,
        "repeat_workload": split,
    }
    hostpath_report = {
        "mode": "hostpath-ab",
        "offered_rate_rps": args.rate,
        "requests": args.requests,
        "max_request": args.max_request,
        "buckets": [int(b) for b in args.buckets.split(",")],
        "replicas": args.replicas,
        "wire_ab": {
            "rungs": rungs,
            "goodput_ratio_binary_vs_json": goodput_ratio,
            "p50_ratio_binary_vs_json": p50_ratio,
        },
        "cache_round": cache_round,
    }
    with open(args.hostpath_report, "w") as f:
        json.dump(hostpath_report, f, indent=2)
    print(f"hostpath report: {args.hostpath_report}")
    for fmt in ("json", "binary"):
        r = rungs[fmt]
        print(
            f"  wire {fmt}: {r['goodput_rps']:.1f} goodput req/s, "
            f"p50 {r['latency_ms']['p50']:.2f} ms / "
            f"p99 {r['latency_ms']['p99']:.2f} ms, "
            f"{r['rejected']} rejected, {r['timed_out']} timed out"
        )
    print(
        "  binary vs json: goodput "
        + (f"{goodput_ratio:.2f}x" if goodput_ratio else "n/a")
        + ", p50 "
        + (f"{p50_ratio:.2f}x" if p50_ratio else "n/a")
    )
    print(
        f"  cache round: {hits} hit / {server_cache.get('miss', 0)} miss "
        f"/ {server_cache.get('coalesced', 0)} coalesced "
        f"(hit rate {server_cache.get('hit_rate', 0.0):.1%}), "
        "hit-path p99 "
        + (f"{repeat_p99:.2f} ms" if repeat_p99 is not None else "n/a")
        + " vs miss-path p99 "
        + (f"{first_p99:.2f} ms" if first_p99 is not None else "n/a")
    )
    return rc


def run_devicepath(args) -> int:
    """The device hot-path A/B (docs/SERVING.md; the PR-19 twin of
    --hostpath-ab): the SAME open-loop trace against a fresh self-serve
    stack twice, once bucketed (pow2 padding ladder) and once packed
    (ragged rows-capacity buffer + segment ids), at equal offered rate.

    What packing must show, and what this round enforces:

    - **fewer warmup executables** — the packed capacity ladder
      collapses the pow2 rung grid, so the packed rung's warmup trace
      count must be strictly below the bucketed rung's;
    - **better fill** — mean fill ratio (live rows / dispatched rows,
      the corrected accounting) must improve, optionally above a hard
      floor (``--devicepath-min-fill``);
    - **equal-or-better client p99** within ``--devicepath-p99-slack``
      (default 1.0 = literally equal-or-better; smokes on noisy CI
      hosts may loosen it);
    - the standing hostpath invariants: zero lost responses, zero
      transport errors, zero duplicated outcomes, zero post-warmup
      compiles — splitting a request across two packed batches must
      never lose or double-answer it.

    The section merges into ``--hostpath-report`` (BENCH_hostpath.json)
    under ``"device_ab"`` so one file carries both hot-path ledgers.
    """
    if not args.open_loop:
        raise SystemExit(
            "--devicepath-ab is an open-loop A/B (fill and padding waste "
            "only mean something at a FIXED offered rate; a closed loop "
            "would re-close around the faster path); add --open-loop "
            "--rate R"
        )
    rc = 0
    rungs: dict[str, dict] = {}
    for mode in ("bucketed", "packed"):
        rung_args = argparse.Namespace(**{
            **vars(args),
            "packed": mode == "packed",
            "fill_wait_ms": (
                args.fill_wait_ms if mode == "packed" else None
            ),
            "repeat_dist": None, "response_cache": None,
        })
        print(f"--- devicepath rung: {mode} ---")
        server, sink, url = _spin_self_serve(rung_args, replicas=args.replicas)
        try:
            _status, before = fetch_json(f"{url}/metrics")
            raw = _drive(rung_args, url)
            _status, after = fetch_json(f"{url}/metrics")
            if mode == "packed" and args.prom_dump:
                with open(args.prom_dump, "w") as f:
                    f.write(fetch_text(f"{url}/metrics?format=prom"))
                print(f"prometheus exposition (packed rung): {args.prom_dump}")
        finally:
            _teardown_self_serve(server, sink)
        report = summarize(raw, before, after)
        row, rung_rc = _rung_verdict(args, raw, before, after, report, mode)
        rc = rc or rung_rc
        # Warmup executable count: the compiles gauge right after warmup
        # IS the rung grid (variants x rungs x replicas traces) — the
        # ladder-collapse win the packed rung must show.
        row["warmup_executables"] = before.get("compiles")
        row["fill_ratio_mean"] = (
            (after.get("pipeline") or {}).get("fill_ratio_mean")
        )
        row["batch_occupancy_pct"] = after.get("batch_occupancy_pct")
        rungs[mode] = row
    b, p = rungs["bucketed"], rungs["packed"]
    if (
        b["warmup_executables"] is not None
        and p["warmup_executables"] is not None
        and p["warmup_executables"] >= b["warmup_executables"]
    ):
        print(
            f"DEVICEPATH FAIL: packed warmed {p['warmup_executables']} "
            f"executable(s), not fewer than bucketed's "
            f"{b['warmup_executables']} — the capacity ladder did not "
            "collapse"
        )
        rc = 1
    if (
        b["fill_ratio_mean"] is not None
        and p["fill_ratio_mean"] is not None
        and p["fill_ratio_mean"] <= b["fill_ratio_mean"]
    ):
        print(
            f"DEVICEPATH FAIL: packed mean fill "
            f"{p['fill_ratio_mean']:.3f} did not improve on bucketed's "
            f"{b['fill_ratio_mean']:.3f}"
        )
        rc = 1
    if (
        args.devicepath_min_fill is not None
        and (p["fill_ratio_mean"] or 0.0) < args.devicepath_min_fill
    ):
        print(
            f"DEVICEPATH FAIL: packed mean fill "
            f"{p['fill_ratio_mean']:.3f} under the --devicepath-min-fill "
            f"floor {args.devicepath_min_fill:g}"
        )
        rc = 1
    p99_b = b["latency_ms"]["p99"]
    p99_p = p["latency_ms"]["p99"]
    if p99_b and p99_p and p99_p > p99_b * args.devicepath_p99_slack:
        print(
            f"DEVICEPATH FAIL: packed client p99 {p99_p:.2f} ms worse "
            f"than bucketed {p99_b:.2f} ms x slack "
            f"{args.devicepath_p99_slack:g}"
        )
        rc = 1
    device_ab = {
        "offered_rate_rps": args.rate,
        "requests": args.requests,
        "max_request": args.max_request,
        "buckets": [int(x) for x in args.buckets.split(",")],
        "replicas": args.replicas,
        "fill_wait_ms": args.fill_wait_ms,
        "rungs": rungs,
        "warmup_executables_bucketed": b["warmup_executables"],
        "warmup_executables_packed": p["warmup_executables"],
        "fill_ratio_mean_bucketed": b["fill_ratio_mean"],
        "fill_ratio_mean_packed": p["fill_ratio_mean"],
        "p99_ratio_packed_vs_bucketed": (
            p99_p / p99_b if p99_b else None
        ),
        "passed": rc == 0,
    }
    # One hot-path ledger: merge into the hostpath report rather than
    # scattering a second bench file (the host A/B's sections survive).
    doc = {"mode": "hostpath-ab"}
    if os.path.exists(args.hostpath_report):
        try:
            with open(args.hostpath_report) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            pass
    doc["device_ab"] = device_ab
    with open(args.hostpath_report, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"devicepath report: {args.hostpath_report} (device_ab section)")
    for mode in ("bucketed", "packed"):
        r = rungs[mode]
        fill = r["fill_ratio_mean"]
        print(
            f"  {mode}: {r['warmup_executables']} warmup executable(s), "
            "mean fill "
            + (f"{100.0 * fill:.1f}%" if fill is not None else "n/a")
            + f", p50 {r['latency_ms']['p50']:.2f} ms / "
            f"p99 {r['latency_ms']['p99']:.2f} ms, "
            f"{r['rejected']} rejected, {r['timed_out']} timed out"
        )
    return rc


# ---------------------------------------------------------------------------
# Model-registry drive modes (docs/SERVING.md model registry):
# --swap-at-s T fires a live /admin/swap T seconds into the drive and
# fails on any lost request, torn response (logits matching neither the
# full-old nor the full-new weights), or post-warmup compile;
# --canary-sweep P1,P2 climbs the canary rungs verifying the EXACT
# deterministic split against the offline assignment recomputation.


def _spin_registry_serve(args):
    """Self-serve stack in registry mode: a temp registry directory with
    v1 (seed) and v2 (seed+1) published, the engine serving v1, and the
    rollout controller wired in.  The response cache stays OFF so every
    outcome is a real dispatch the verdicts can count."""
    import shutil
    import tempfile

    from pytorch_mnist_ddp_tpu.models.net import init_params
    from pytorch_mnist_ddp_tpu.obs.events import open_sink
    from pytorch_mnist_ddp_tpu.serving import InferenceEngine, ServingMetrics
    from pytorch_mnist_ddp_tpu.serving.registry import ModelRegistry
    from pytorch_mnist_ddp_tpu.serving.rollout import RolloutController
    from pytorch_mnist_ddp_tpu.serving.server import make_server
    from pytorch_mnist_ddp_tpu.utils.checkpoint import (
        model_state_dict,
        save_state_dict,
    )
    from pytorch_mnist_ddp_tpu.utils.rng import root_key, split_streams

    metrics = ServingMetrics()
    buckets = [int(b) for b in args.buckets.split(",")]
    sink = open_sink(args.telemetry_dir)
    regdir = tempfile.mkdtemp(prefix="loadgen_registry_")
    registry = ModelRegistry(regdir, sink=sink)
    base_seed = args.seed or 1
    for i, seed in enumerate((base_seed, base_seed + 1), start=1):
        params = init_params(split_streams(root_key(seed))["init"])
        path = os.path.join(regdir, f"v{i}.npz")
        save_state_dict(model_state_dict(params), path, format="npz")  # jaxlint: disable=JL014 -- bounded two-version publish, not a step loop
        registry.publish("mnist", f"v{i}", path, make_default=(i == 1))
    entry = registry.resolve()
    engine = InferenceEngine(
        registry.load(entry), buckets=buckets, metrics=metrics,
        version=entry.version,
    )
    print(
        f"registry self-serve: {regdir} (v1 seed {base_seed} default, "
        f"v2 seed {base_seed + 1}); warming buckets {list(engine.buckets)}"
    )
    engine.warmup()
    rollout = RolloutController(
        registry, engine, metrics=metrics, sink=sink,
    )
    server = make_server(
        engine, metrics, port=0, sink=sink, rollout=rollout,
        linger_ms=args.linger_ms, queue_depth=args.queue_depth,
        timeout_ms=args.timeout_ms, max_inflight=args.max_inflight,
        adaptive_linger=not args.no_adaptive_linger,
    )
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    print(f"registry self-serve: {url}")
    cleanup = lambda: shutil.rmtree(regdir, ignore_errors=True)  # noqa: E731
    return server, sink, url, engine, cleanup


def _registry_payloads(args, count: int):
    """Distinct seeded payloads: ``(raw_pixels, model_ready_rows)`` per
    request, sizes cycling 1..max_request.  The model-ready bytes are
    what the server hashes for the canary split, so the offline
    assignment audit recomputes from ``x4.tobytes()`` exactly."""
    import numpy as np

    rng = np.random.RandomState(args.seed or 0)
    payloads = []
    for i in range(count):
        n = 1 + i % max(1, args.max_request)
        raw = rng.randint(0, 256, (n, 784)).astype(np.float32)
        payloads.append((raw, raw.reshape(-1, 28, 28, 1)))
    return payloads


def _registry_predict(url, raw, timeout):
    import numpy as np

    status, body = fetch_json(
        f"{url}/predict",
        {"instances": raw.tolist(), "normalized": True,
         "return_log_probs": True},
        timeout=timeout,
    )
    if status != 200:
        return status, None
    return status, np.asarray(body.get("log_probs"), np.float32)


def run_registry(args) -> int:
    """The swap/canary drive: see the module docstring's registry
    section.  Writes ``--registry-report`` and exits nonzero on any
    lost/torn/misrouted outcome or post-warmup compile."""
    import numpy as np

    rc = 0
    report: dict = {"mode": "registry"}
    server, sink, url, engine, cleanup = _spin_registry_serve(args)
    try:
        compiles0 = engine.compile_count()
        payloads = _registry_payloads(args, min(args.requests, 48))
        expected_v1 = [
            engine.predict_logits(x4).copy() for _raw, x4 in payloads
        ]

        # -- swap round -------------------------------------------------------
        if args.swap_at_s is not None:
            results: list[tuple[int, int, object]] = []
            swap_result: dict = {}
            stop = threading.Event()

            def do_swap():
                status, body = fetch_json(
                    f"{url}/admin/swap", {"version": "v2"},
                    timeout=args.timeout_s,
                )
                swap_result["status"] = status
                swap_result["body"] = body

            timer = threading.Timer(args.swap_at_s, do_swap)
            timer.start()
            deadline = time.perf_counter() + 2.0 * args.swap_at_s + 0.5

            def hammer(wid, nworkers=4):
                i = wid
                while time.perf_counter() < deadline and not stop.is_set():
                    k = i % len(payloads)
                    i += nworkers
                    status, logits = _registry_predict(
                        url, payloads[k][0], args.timeout_s
                    )
                    results.append((k, status, logits))

            workers = [
                threading.Thread(target=hammer, args=(w,)) for w in range(4)
            ]
            for w in workers:
                w.start()
            for w in workers:
                w.join(timeout=args.timeout_s + 2 * args.swap_at_s)
            timer.join()
            expected_v2 = [
                engine.predict_logits(x4).copy() for _raw, x4 in payloads
            ]
            non_200 = sum(1 for _k, s, _l in results if s != 200)
            torn = sum(
                1 for k, s, logits in results
                if s == 200 and not (
                    np.array_equal(logits, expected_v1[k])
                    or np.array_equal(logits, expected_v2[k])
                )
            )
            served_new = sum(
                1 for k, s, logits in results
                if s == 200 and np.array_equal(logits, expected_v2[k])
            )
            added = engine.compile_count() - compiles0
            swap_row = {
                "swap_at_s": args.swap_at_s,
                "requests": len(results),
                "lost_or_failed": non_200,
                "torn": torn,
                "served_old": len(results) - non_200 - torn - served_new,
                "served_new": served_new,
                "swap_http_status": swap_result.get("status"),
                "additional_compiles": added,
            }
            report["swap"] = swap_row
            if swap_result.get("status") != 200:
                print(f"REGISTRY FAIL [swap]: /admin/swap answered "
                      f"{swap_result.get('status')} "
                      f"({swap_result.get('body')})")
                rc = 1
            if non_200:
                print(f"REGISTRY FAIL [swap]: {non_200} request(s) "
                      "without a 200 outcome during the swap window")
                rc = 1
            if torn:
                print(f"REGISTRY FAIL [swap]: {torn} TORN response(s) — "
                      "logits match neither the old nor the new weights")
                rc = 1
            if not served_new:
                print("REGISTRY FAIL [swap]: no request ever served the "
                      "new weights — the swap never landed in the drive "
                      "window")
                rc = 1
            if added:
                print(f"REGISTRY FAIL [swap]: {added} post-warmup "
                      "compile(s) — the weight republish re-traced")
                rc = 1
            if rc == 0:
                print(
                    f"swap: {len(results)} requests, "
                    f"{swap_row['served_old']} old / {served_new} new, "
                    "0 lost, 0 torn, 0 compiles"
                )

        # -- canary sweep ----------------------------------------------------
        if args.canary_sweep:
            from pytorch_mnist_ddp_tpu.serving.rollout import (
                canary_assignment,
            )

            # After a swap round the primary is v2; canary the OTHER
            # version so the split is between distinguishable weights.
            _status, desc = fetch_json(f"{url}/admin/rollout", {})
            primary = desc["version"]
            canary_version = "v2" if primary == "v1" else "v1"
            canary_rows = []
            compiles_before = engine.compile_count()
            for pct_s in str(args.canary_sweep).split(","):
                pct = float(pct_s)
                status, body = fetch_json(
                    f"{url}/admin/canary",
                    {"version": canary_version, "pct": pct},
                    timeout=args.timeout_s,
                )
                if status != 200:
                    print(f"REGISTRY FAIL [canary {pct:g}%]: /admin/canary "
                          f"answered {status} ({body})")
                    rc = 1
                    break
                expected_pin = [
                    engine.predict_logits(
                        x4, dtype=f"f32@{canary_version}"
                    ).copy()
                    for _raw, x4 in payloads
                ]
                expected_pri = [
                    engine.predict_logits(x4).copy()
                    for _raw, x4 in payloads
                ]
                misrouted = failed = canary_served = 0
                for k, (raw, x4) in enumerate(payloads):
                    assigned = canary_assignment(x4.tobytes(), pct)
                    status, logits = _registry_predict(
                        url, raw, args.timeout_s
                    )
                    if status != 200:
                        failed += 1
                        continue
                    want = expected_pin[k] if assigned else expected_pri[k]
                    if not np.array_equal(logits, want):
                        misrouted += 1
                    canary_served += bool(assigned)
                row = {
                    "pct": pct,
                    "requests": len(payloads),
                    "expected_canary": canary_served,
                    "failed": failed,
                    "misrouted": misrouted,
                }
                canary_rows.append(row)
                if failed or misrouted:
                    print(
                        f"REGISTRY FAIL [canary {pct:g}%]: {failed} "
                        f"failed, {misrouted} response(s) not matching "
                        "the deterministic assignment"
                    )
                    rc = 1
                else:
                    print(
                        f"canary {pct:g}%: {canary_served}/{len(payloads)}"
                        " split to the canary, exact deterministic match"
                    )
            status, _body = fetch_json(
                f"{url}/admin/rollback", {"reason": "sweep_done"},
                timeout=args.timeout_s,
            )
            if status != 200:
                print(f"REGISTRY FAIL [canary]: rollback answered {status}")
                rc = 1
            added = engine.compile_count() - compiles_before
            if added:
                print(f"REGISTRY FAIL [canary]: {added} post-warmup "
                      "compile(s) across the sweep")
                rc = 1
            report["canary_sweep"] = {
                "version": canary_version,
                "rungs": canary_rows,
                "additional_compiles": added,
            }
        _status, rollout_desc = fetch_json(f"{url}/admin/rollout", {})
        report["final_rollout"] = rollout_desc
        report["additional_compiles"] = engine.compile_count() - compiles0
    finally:
        _teardown_self_serve(server, sink)
        cleanup()
    with open(args.registry_report, "w") as f:
        json.dump(report, f, indent=2)
    print(f"registry report: {args.registry_report}")
    print(f"REGISTRY {'PASS' if rc == 0 else 'FAIL'}")
    return rc


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    parser.add_argument(
        "--url", default=None,
        help="serving endpoint (http://host:port); omitted = --self-serve",
    )
    parser.add_argument(
        "--self-serve", action="store_true",
        help="spin up an in-process server on a loopback port (fresh "
        "seed weights; the default when --url is omitted)",
    )
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument(
        "--concurrency", type=int, default=8,
        help="closed-loop client threads; in --open-loop mode, the cap on "
        "simultaneously outstanding requests (size it above rate x "
        "latency — a saturated pool shows up as client-side queueing in "
        "the latency percentiles, which are measured from the scheduled "
        "arrival)",
    )
    parser.add_argument(
        "--open-loop", action="store_true",
        help="Poisson arrivals at --rate req/s, independent of "
        "completions (closed-loop client threads otherwise)",
    )
    parser.add_argument(
        "--rate", type=float, default=200.0,
        help="open-loop offered arrival rate, requests/second",
    )
    parser.add_argument(
        "--max-request", type=int, default=16,
        help="request sizes are drawn uniformly from [1, this]",
    )
    parser.add_argument(
        "--dtype", default="f32", choices=("f32", "bf16", "int8"),
        help="route every request to this serving variant (the /predict "
        "\"dtype\" field) — the reduced-precision A/B knob; in "
        "--self-serve mode the variant is warmed and parity-gated "
        "before the run (docs/SERVING.md)",
    )
    parser.add_argument(
        "--wire", default="json", choices=("json", "binary"),
        help="request wire format (docs/SERVING.md): json = the default "
        "text protocol; binary = application/x-mnist-f32 (fixed header "
        "+ raw float32 rows, responses as raw logits bytes) — the "
        "host-path A/B knob.  Bodies are pre-encoded before the "
        "arrival clock either way",
    )
    parser.add_argument(
        "--repeat-dist", default=None, metavar="zipf:S[:K]",
        help="repeated-payload workload: draw each request's payload "
        "from a catalog of K distinct payloads (default 16) with "
        "zipf(S) popularity — the realistic hit distribution for the "
        "response-cache A/B; the report gains a first-occurrence vs "
        "repeat client percentile split",
    )
    parser.add_argument(
        "--response-cache", type=int, default=None, metavar="N",
        help="--self-serve mode: enable the server's content-addressed "
        "response cache + single-flight dedup, bounded at N entries "
        "(serving/cache.py; the /predict --response-cache flag)",
    )
    parser.add_argument(
        "--hostpath-ab", action="store_true",
        help="host hot-path A/B (docs/SERVING.md): drive the SAME "
        "open-loop trace with --wire json then --wire binary at equal "
        "offered rate, then a zipf repeat workload with the response "
        "cache on; write goodput/latency ratios + cache hit stats to "
        "--hostpath-report and FAIL on lost/duplicated responses, "
        "post-warmup compiles, zero hits, or a hit-path p99 not under "
        "the miss-path p99",
    )
    parser.add_argument(
        "--hostpath-report", default="BENCH_hostpath.json",
        help="where --hostpath-ab writes its report",
    )
    parser.add_argument(
        "--cache-rate", type=float, default=None, metavar="RPS",
        help="offered rate for --hostpath-ab's cache round (default "
        "--rate).  The wire A/B deliberately saturates the host; the "
        "cache round wants a rate the MISS path can sustain, so the "
        "hit/miss latency split measures the cache, not client-side "
        "queueing",
    )
    parser.add_argument(
        "--packed", action="store_true",
        help="--self-serve mode: packed ragged batching (requests "
        "concatenated into one rows-capacity buffer + segment ids "
        "instead of pow2 padding; docs/SERVING.md)",
    )
    parser.add_argument(
        "--fill-wait-ms", type=float, default=None,
        help="packed mode: how long a forming batch may wait for more "
        "rows before dispatching part-full (the linger ceiling in "
        "packed mode)",
    )
    parser.add_argument(
        "--int8-impl", default="dot", choices=("dot", "pallas"),
        help="--self-serve int8 dense-head lowering (dot = reference "
        "GEMMs, pallas = fused kernel with off-TPU fallback)",
    )
    parser.add_argument(
        "--devicepath-ab", action="store_true",
        help="device hot-path A/B (docs/SERVING.md; PR-19): the SAME "
        "open-loop trace bucketed then packed at equal offered rate; "
        "merge the rung table into --hostpath-report under 'device_ab' "
        "and FAIL unless packed warms strictly fewer executables, "
        "improves mean fill, holds client p99 within "
        "--devicepath-p99-slack, and loses/duplicates nothing",
    )
    parser.add_argument(
        "--devicepath-p99-slack", type=float, default=1.0,
        help="multiplier on the bucketed rung's client p99 the packed "
        "rung must stay within (1.0 = literally equal-or-better; CI "
        "smokes on noisy shared hosts may loosen)",
    )
    parser.add_argument(
        "--devicepath-min-fill", type=float, default=None,
        help="optional hard floor on the packed rung's mean fill ratio "
        "(the SLO gate ratchets this permanently; here it guards ad-hoc "
        "A/Bs)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--timeout-s", type=float, default=30.0)
    parser.add_argument(
        "--buckets", default="8,16,32",
        help="bucket ladder for --self-serve mode",
    )
    parser.add_argument(
        "--linger-ms", type=float, default=2.0,
        help="batcher linger for --self-serve mode",
    )
    parser.add_argument(
        "--queue-depth", type=int, default=64,
        help="admission bound for --self-serve mode",
    )
    parser.add_argument(
        "--timeout-ms", type=float, default=1000.0,
        help="per-request server-side deadline for --self-serve mode; "
        "raise it (with --queue-depth) for no-shed capacity A/Bs where "
        "every request must complete",
    )
    parser.add_argument(
        "--max-inflight", type=int, default=2,
        help="in-flight window for --self-serve mode (1 = serial PR-3 "
        "pipeline, for A/B throughput comparisons)",
    )
    parser.add_argument(
        "--no-adaptive-linger", action="store_true",
        help="pin the linger at --linger-ms in --self-serve mode",
    )
    parser.add_argument(
        "--no-deadline-close", action="store_true",
        help="--self-serve mode: disable deadline-aware batch close "
        "(batches then honor the global linger even when the oldest "
        "member's deadline budget is nearly spent)",
    )
    parser.add_argument(
        "--qos-mix", default=None, metavar="CLASS=FRAC,...",
        help="per-request QoS class mix, e.g. interactive=0.8,batch=0.2: "
        "each request is labeled from this distribution (seeded) and "
        "the label is sent as the /predict \"qos\" field; the report "
        "gains per-class latency percentiles (docs/SERVING.md)",
    )
    parser.add_argument(
        "--hedge", action="store_true",
        help="--self-serve pool mode: enable hedged dispatch with the "
        "per-class p99 digest delay (docs/SERVING.md tail latency)",
    )
    parser.add_argument(
        "--hedge-delay-ms", type=float, default=None, metavar="MS",
        help="fixed hedge delay in ms (implies --hedge); straggler "
        "requests re-dispatch to a second replica after this wait, "
        "first completion wins",
    )
    parser.add_argument(
        "--ab-tail", action="store_true",
        help="tail-latency A/B: drive the SAME open-loop trace against "
        "a feature-off pool (no QoS, global linger, no hedging) and a "
        "feature-on pool (QoS mix + deadline-aware close + hedging), "
        "report per-class p50/p95/p99 deltas to --tail-report, and FAIL "
        "on any lost response or duplicated client-visible outcome",
    )
    parser.add_argument(
        "--tail-report", default="BENCH_tail.json",
        help="where --ab-tail writes its report",
    )
    parser.add_argument(
        "--telemetry-dir", default=None,
        help="--self-serve mode: write serving JSONL telemetry here "
        "(summarize with tools/perf_report.py --telemetry)",
    )
    parser.add_argument(
        "--prom-dump", default=None,
        help="after the run, save the endpoint's Prometheus exposition "
        "(/metrics?format=prom) to this file",
    )
    parser.add_argument(
        "--replicas", type=int, default=None, metavar="N",
        help="--self-serve mode: serve an N-replica per-device engine "
        "pool behind the queue-aware router instead of one engine "
        "(0 = one per visible device, as in the serving CLI; "
        "docs/SERVING.md scale-out)",
    )
    parser.add_argument(
        "--replica-shapes", default=None, metavar="SPEC",
        help="--self-serve pool mode: comma-separated per-replica shard "
        "shape, e.g. 'tp4,dp,dp,dp,dp' — tp/vtp/ep/pp replicas span "
        "disjoint device blocks and are parity-gated against the "
        "single-device reference at warmup; count must match --replicas "
        "(docs/SERVING.md sharded replicas)",
    )
    parser.add_argument(
        "--router-policy", default="cost",
        choices=("roundrobin", "least-loaded", "cost"),
        help="replica placement policy for --replicas / --replicas-sweep",
    )
    parser.add_argument(
        "--replicas-sweep", default=None, metavar="N1,N2,...",
        help="scale-out sweep: run the SAME workload against self-serve "
        "pools of each listed replica count and report goodput vs. "
        "replicas at fixed p99 with scaling efficiency "
        "(--scaleout-report; --prom-dump saves the last rung's "
        "exposition)",
    )
    parser.add_argument(
        "--scaleout-report", default="BENCH_serving_scaleout.json",
        help="where --replicas-sweep writes its report",
    )
    parser.add_argument(
        "--fleet-sweep", default=None, metavar="N1,N2,...",
        help="multi-PROCESS fleet sweep (docs/SERVING.md fleet section): "
        "bring up a fleet of each listed backend count (real serving "
        "subprocesses sharing one AOT cache, or fakes with "
        "--fleet-fake), drive the SAME open-loop trace through the "
        "front tier, then run a recovery-under-kill round at the top "
        "rung — goodput/p99/scaling-efficiency per count plus the "
        "recovery receipt land in --fleet-report; requires --open-loop",
    )
    parser.add_argument(
        "--fleet-fake", action="store_true",
        help="with --fleet-sweep: in-process fake backends with SERIAL "
        "capacity over real sockets — the structural scaling pin for "
        "host-bound boxes (N real jax processes on 2 cores flatten at "
        "the host bound; the fakes do not)",
    )
    parser.add_argument(
        "--fleet-service-ms", type=float, default=20.0,
        help="fake-backend per-request service time (--fleet-fake)",
    )
    parser.add_argument(
        "--no-fleet-kill", action="store_true",
        help="skip the recovery-under-kill round after the sweep",
    )
    parser.add_argument(
        "--no-fleet-autoscale", action="store_true",
        help="skip the autoscale round (--fleet-fake sweeps only: "
        "1 backend under sustained over-capacity load must scale to 2, "
        "then drain back at idle with nothing lost)",
    )
    parser.add_argument(
        "--fleet-report", default="BENCH_fleet.json",
        help="where --fleet-sweep writes its report",
    )
    parser.add_argument(
        "--fleet-base-port", type=int, default=18411,
        help="first real-backend port for --fleet-sweep",
    )
    parser.add_argument(
        "--fleet-max-503-rate", type=float, default=0.25,
        help="maximum tolerated client-visible 503 fraction during the "
        "kill round (the bounded-shed contract at fleet scope)",
    )
    parser.add_argument(
        "--fleet-recovery-wait", type=float, default=60.0,
        help="post-drive wait for the killed backend's replacement to "
        "serve again before the kill round fails",
    )
    parser.add_argument(
        "--aot-cache", default=None, metavar="DIR",
        help="--self-serve mode: shared serialized-executable store for "
        "the engine(s) (compile/aot.ExecutableStore; a warm pool start "
        "deserializes every replica's grid with zero traces)",
    )
    parser.add_argument(
        "--chaos", default=None, metavar="SPEC",
        help="drive a fault schedule against the self-serve pool while "
        "the workload runs (requires --replicas; docs/ROBUSTNESS.md "
        "grammar, e.g. 'fail:launch:r1:count=6;hang:complete:r0:for=2'). "
        "The run then FAILS on any lost or duplicated response, any "
        "transport error, a 503 rate above --chaos-max-503-rate, or any "
        "post-restart compile, and the report gains a \"chaos\" section "
        "with restarts, recovery times, and final replica states",
    )
    parser.add_argument(
        "--chaos-seed", type=int, default=0,
        help="seed for the fault schedule's probabilistic clauses and "
        "the supervisor's backoff jitter (determinism receipt)",
    )
    parser.add_argument(
        "--chaos-max-503-rate", type=float, default=0.25,
        help="maximum tolerated client-visible 503 fraction under "
        "--chaos (the bounded-shed contract)",
    )
    parser.add_argument(
        "--chaos-stall-timeout", type=float, default=0.5,
        help="supervisor completion-stall threshold under --chaos "
        "(seconds; compressed from the serving CLI's 5s default to "
        "match a compressed fault schedule)",
    )
    parser.add_argument(
        "--chaos-recovery-wait", type=float, default=15.0,
        help="after the workload, wait up to this long (driving probe "
        "requests through half-open circuits) for every replica to "
        "heal before the final metrics/prom snapshot",
    )
    parser.add_argument("--report", default="BENCH_serving.json")
    parser.add_argument(
        "--no-check-compiles", action="store_true",
        help="don't fail when the run triggered additional compiles",
    )
    parser.add_argument(
        "--swap-at-s", type=float, default=None,
        help="registry drive: fire a live /admin/swap to v2 this many "
        "seconds into a closed-loop hammer; FAIL on any lost request, "
        "torn response, or post-warmup compile",
    )
    parser.add_argument(
        "--canary-sweep", default=None,
        help="registry drive: comma-separated canary percentages (e.g. "
        "25,50); each rung verifies the EXACT deterministic split "
        "against the offline assignment recomputation, then rolls back",
    )
    parser.add_argument(
        "--registry-report", default="BENCH_registry.json",
        help="where the registry drive writes its verdict JSON",
    )
    args = parser.parse_args(argv)

    if args.url and args.replicas is not None:
        # Silently measuring a remote single endpoint while the report
        # claims N replicas is exactly the confusion a benchmark tool
        # must not allow.
        parser.error("--replicas is --self-serve only; a --url endpoint "
                     "chooses its own replica count")
    if args.chaos and (args.url or args.replicas_sweep):
        parser.error("--chaos drives a single self-serve pool; drop "
                     "--url / --replicas-sweep")
    if args.chaos and args.replicas is None:
        parser.error("--chaos needs --replicas N: fault tolerance is a "
                     "pool property (a lone engine has no survivors to "
                     "retry on)")
    if args.hedge or args.hedge_delay_ms is not None:
        if args.url:
            parser.error("--hedge is --self-serve pool only; a --url "
                         "endpoint configures its own hedging")
        if args.replicas is None and not args.ab_tail and not args.replicas_sweep:
            # The single-engine self-serve branch has no hedger; running
            # it under a --hedge flag would measure an unhedged engine
            # while the operator believes otherwise (the serving CLI
            # hard-errors on the same combination).
            parser.error("--hedge needs --replicas N (>= 2): a lone "
                         "engine has no second replica to hedge onto")
    if args.response_cache is not None and args.url:
        parser.error("--response-cache is --self-serve only; a --url "
                     "endpoint configures its own cache")
    if args.response_cache is not None and args.response_cache < 1:
        # Fail at the flag surface, not after minutes of warmup (the
        # serving CLI's pre-flight rule).
        parser.error(f"--response-cache must be >= 1, got "
                     f"{args.response_cache}")
    if args.swap_at_s is not None or args.canary_sweep:
        if args.url or args.replicas is not None or args.replicas_sweep \
                or args.chaos or args.ab_tail or args.fleet_sweep \
                or args.hostpath_ab or args.devicepath_ab:
            parser.error("--swap-at-s / --canary-sweep drive their own "
                         "single-engine registry stack; drop --url / "
                         "--replicas / --replicas-sweep / --chaos / "
                         "--ab-tail / --fleet-sweep / --hostpath-ab / "
                         "--devicepath-ab")
        if args.swap_at_s is not None and args.swap_at_s <= 0:
            parser.error(f"--swap-at-s must be > 0, got {args.swap_at_s}")
        if args.response_cache is not None:
            parser.error("the registry drive keeps the response cache "
                         "off so every outcome is a countable dispatch; "
                         "drop --response-cache")
        return run_registry(args)
    if args.hostpath_ab:
        if args.url or args.replicas_sweep or args.chaos or args.ab_tail \
                or args.fleet_sweep:
            parser.error("--hostpath-ab drives its own self-serve "
                         "stacks; drop --url / --replicas-sweep / "
                         "--chaos / --ab-tail / --fleet-sweep")
        return run_hostpath(args)
    if args.devicepath_ab:
        if args.url or args.replicas_sweep or args.chaos or args.ab_tail \
                or args.fleet_sweep:
            parser.error("--devicepath-ab drives its own self-serve "
                         "stacks; drop --url / --replicas-sweep / "
                         "--chaos / --ab-tail / --fleet-sweep")
        if args.packed:
            parser.error("--devicepath-ab toggles packing itself; drop "
                         "--packed")
        return run_devicepath(args)
    if args.fleet_sweep:
        if args.url or args.replicas_sweep or args.chaos or args.ab_tail:
            parser.error("--fleet-sweep drives its own fleets; drop "
                         "--url / --replicas-sweep / --chaos / --ab-tail")
        if args.replicas is not None:
            parser.error("--fleet-sweep backends choose their own "
                         "replica layout; drop --replicas")
        return run_fleet_sweep(args)
    if args.ab_tail:
        if args.url or args.replicas_sweep or args.chaos:
            parser.error("--ab-tail drives its own pair of self-serve "
                         "pools; drop --url / --replicas-sweep / --chaos")
        return run_ab_tail(args)
    if args.replicas_sweep:
        if args.url:
            parser.error("--replicas-sweep drives self-serve pools; "
                         "drop --url")
        return run_replica_sweep(args)

    server = None
    sink = None
    if args.url and not args.self_serve:
        url = args.url.rstrip("/")
    else:
        server, sink, url = _spin_self_serve(args, replicas=args.replicas)

    chaos_section = None
    try:
        if args.chaos:
            raw, before, after, chaos_section = run_chaos(
                args, server, sink, url
            )
        else:
            _status, before = fetch_json(f"{url}/metrics")
            raw = _drive(args, url)
            _status, after = fetch_json(f"{url}/metrics")
        if args.prom_dump:
            with open(args.prom_dump, "w") as f:
                f.write(fetch_text(f"{url}/metrics?format=prom"))
            print(f"prometheus exposition: {args.prom_dump}")
    finally:
        _teardown_self_serve(server, sink)

    report = summarize(raw, before, after)
    chaos_rc = 0
    if chaos_section is not None:
        # The chaos verdict (docs/ROBUSTNESS.md): every submitted
        # request got exactly one terminal HTTP outcome (no losses, no
        # transport errors = no duplicated/abandoned work visible to a
        # client), shed stayed bounded, and the pool healed.
        results = raw["results"]
        lost = args.requests - len(results)
        transport = sum(1 for status, *_ in results if status == 0)
        rate_503 = (
            report["rejected"] / len(results) if results else 0.0
        )
        chaos_section["lost"] = lost
        chaos_section["transport_errors"] = transport
        chaos_section["rejected_rate"] = rate_503
        report["chaos"] = chaos_section
        if lost or transport:
            print(
                f"CHAOS FAIL: {lost} request(s) without a terminal "
                f"outcome, {transport} transport error(s)"
            )
            chaos_rc = 1
        if rate_503 > args.chaos_max_503_rate:
            print(
                f"CHAOS FAIL: 503 rate {rate_503:.1%} exceeds the "
                f"--chaos-max-503-rate bound {args.chaos_max_503_rate:.1%}"
            )
            chaos_rc = 1
        if not chaos_section["recovered"]:
            print(
                "CHAOS FAIL: replicas did not settle within "
                f"--chaos-recovery-wait ({chaos_section['replica_states']})"
            )
            chaos_rc = 1
        if chaos_section["unfired"]:
            # A green run whose schedule never fired proves nothing —
            # fail loudly instead of narrating a fault drill that did
            # not happen.
            print(
                "CHAOS FAIL: clause(s) never fired: "
                f"{chaos_section['unfired']} (warmup/aot_load sites are "
                "already past by the time --chaos arms; drive those via "
                "pytest -m faults)"
            )
            chaos_rc = 1
        for clause in chaos_section["unfired_probabilistic"]:
            print(f"chaos: WARNING probabilistic clause never fired: {clause}")
        restarts = chaos_section["restarts"]
        print(
            "chaos: "
            f"{sum(chaos_section['fired'].values())} fault(s) fired, "
            f"restarts {restarts}, "
            f"mean recovery {chaos_section['mean_recovery_s'] or 0.0:.3f} s, "
            f"retries {chaos_section['retries']}, "
            f"503 rate {rate_503:.1%}, lost {lost}, "
            f"final states {chaos_section['replica_states']}"
        )
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)

    lat = report["latency_ms"]
    print(
        f"done in {report['wall_s']:.2f}s ({report['mode']}"
        + (f", dtype {report['dtype']}" if report["dtype"] != "f32" else "")
        + (f", offered {report['offered_rate_rps']:.0f} req/s"
           if report["offered_rate_rps"] else "")
        + "): "
        f"{report['throughput_rps']:.1f} req/s, "
        f"p50 {lat['p50']:.2f} ms / p95 {lat['p95']:.2f} ms / "
        f"p99 {lat['p99']:.2f} ms, "
        f"{report['rejected']} rejected (503), "
        f"occupancy {report['server_batch_occupancy_pct']:.1f}%"
        if report["server_batch_occupancy_pct"] is not None
        else "done (no server occupancy reported)"
    )
    print(f"report: {args.report}")
    extra = report["additional_compiles"]
    if extra is None:
        print("warning: endpoint reports no compile gauge; retrace check skipped")
    elif extra > 0:
        print(
            f"RETRACE: {extra} additional compile(s) during the run — "
            "request shapes escaped the bucket policy"
        )
        if not args.no_check_compiles:
            return 1
    else:
        print("zero additional compiles (bucket firewall held)")
    return chaos_rc


def _lockwatch_gate(rc: int) -> int:
    """Under JAXLINT_LOCKWATCH=1, fail the run if the traced locks
    recorded a lock-order cycle — the runtime half of jaxlint JL019,
    checked against REAL serving traffic after the load completes."""
    from pytorch_mnist_ddp_tpu.analysis import lockwatch

    if not lockwatch.enabled():
        return rc
    try:
        lockwatch.assert_acyclic()
    except lockwatch.LockOrderError as e:
        print(f"LOCK ORDER CYCLE: {e}", file=sys.stderr)
        return rc or 3
    print("lockwatch: lock acquisition order acyclic")
    return rc


if __name__ == "__main__":
    sys.exit(_lockwatch_gate(main()))
