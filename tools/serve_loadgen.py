#!/usr/bin/env python
"""Load generator for the serving subsystem (docs/SERVING.md).

Fires mixed-size /predict requests from concurrent client threads at a
serving endpoint and writes a ``BENCH_serving.json``-style report:
client-side p50/p95/p99 latency, throughput, per-status counts
(including 503 rejections — the backpressure signal), and the server's
own /metrics snapshot before and after the run.

The headline assertion is the retrace firewall: mixed request sizes must
cause ZERO additional compiles beyond the warmed buckets.  The tool
reads the server's ``compiles`` gauge before and after and exits nonzero
if it moved (disable with --no-check-compiles when deliberately probing
an unwarmed ladder).

Two arrival models:

- **closed loop** (default): ``--concurrency`` client threads, each
  firing its next request when the previous answers.  Simple, but the
  server's own latency throttles the offered load — a pipelining win
  shows up as lower latency, not higher pressure.
- **open loop** (``--open-loop``): requests arrive on a Poisson process
  at ``--rate`` req/s *regardless of completions*, the arrival model
  real traffic actually has (and the one that exposes overlap: the
  server must absorb arrivals while earlier batches are still in
  flight).  Offered vs achieved rate both land in the report.

Default mode (``--self-serve``) spins the whole stack up in-process on a
loopback port with fresh seed weights — no checkpoint, no running server,
no network needed: the CI-able smoke path.  Point --url at a real server
to load-test a deployment.  ``--prom-dump PATH`` saves the endpoint's
final Prometheus exposition (the in-flight gauge, stall/fill histograms)
for offline grepping — the CI smoke's hook.

Scale-out (docs/SERVING.md): ``--replicas N`` self-serves an N-replica
per-device engine pool behind the queue-aware router
(``--router-policy``), and ``--replicas-sweep 1,2,4`` runs the same
workload against each count in turn, writing goodput vs. replicas at
fixed p99 plus scaling efficiency to ``BENCH_serving_scaleout.json``.

Usage::

    python tools/serve_loadgen.py                       # self-contained
    python tools/serve_loadgen.py --open-loop --rate 500 --requests 1000
    python tools/serve_loadgen.py --url http://host:8000 \
        --requests 2000 --concurrency 32
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fetch_json(url: str, payload: dict | None = None, timeout: float = 30.0) -> tuple[int, dict]:
    """One HTTP exchange -> (status, parsed body); HTTP errors are data
    here (503 IS the backpressure measurement), so they don't raise."""
    req = urllib.request.Request(
        url,
        data=None if payload is None else json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        try:
            body = json.load(e)
        except Exception:
            body = {}
        return e.code, body


def fetch_text(url: str, timeout: float = 30.0) -> str:
    """GET a text body (the Prometheus exposition for --prom-dump)."""
    req = urllib.request.Request(url, headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode()


def _request_payload(rng: random.Random, n: int, dtype: str = "f32") -> dict:
    payload = {
        "instances": [
            [rng.randint(0, 255) for _ in range(784)] for _ in range(n)
        ]
    }
    if dtype != "f32":
        # The reduced-precision A/B knob (docs/SERVING.md): route every
        # request to one named variant; the default payload stays
        # byte-compatible with pre-dtype servers.
        payload["dtype"] = dtype
    return payload


def run_open_loop(
    url: str,
    requests: int,
    rate: float,
    max_request: int,
    seed: int,
    timeout_s: float,
    max_workers: int,
    dtype: str = "f32",
) -> dict:
    """Poisson arrivals at ``rate`` req/s, fired independently of
    completions, bounded by ``max_workers`` outstanding requests.

    Latency is measured from each request's SCHEDULED arrival, not from
    when an executor thread picks it up — otherwise a saturated worker
    pool silently re-closes the loop and hides client-side queueing from
    the percentiles (the coordinated-omission trap open-loop load
    generation exists to avoid).
    """
    from concurrent.futures import ThreadPoolExecutor

    rng = random.Random(seed)
    sizes = [rng.randint(1, max_request) for _ in range(requests)]
    # Pre-draw the whole arrival schedule so the trace is reproducible
    # from --seed and the firing loop does no RNG work.
    arrivals: list[float] = []
    t = 0.0
    for _ in range(requests):
        t += rng.expovariate(rate)
        arrivals.append(t)

    def one(i: int, scheduled: float) -> tuple[int, float]:
        wrng = random.Random(seed * 1000 + i)
        status, _body = fetch_json(
            f"{url}/predict", _request_payload(wrng, sizes[i], dtype),
            timeout=timeout_s,
        )
        return status, time.perf_counter() - scheduled

    t_start = time.perf_counter()
    last_fired = t_start
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = []
        for i in range(requests):
            delay = t_start + arrivals[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            last_fired = time.perf_counter()
            futures.append(pool.submit(one, i, t_start + arrivals[i]))
        results = [f.result() for f in futures]
    wall = time.perf_counter() - t_start
    # achieved rate from real fire times — if the submission loop could
    # not keep up with the schedule, the report must say so rather than
    # echo the offered rate back.
    fired_span = last_fired - t_start
    return {
        "results": results,
        "wall_s": wall,
        "sizes": sizes,
        "mode": "open-loop",
        "dtype": dtype,
        "offered_rate_rps": rate,
        "achieved_arrival_rate_rps": requests / fired_span if fired_span > 0 else 0.0,
    }


def run_load(
    url: str,
    requests: int,
    concurrency: int,
    max_request: int,
    seed: int,
    timeout_s: float,
    dtype: str = "f32",
) -> dict:
    """Drive the endpoint; returns raw per-request (status, latency_s)."""
    rng = random.Random(seed)
    # Pre-generate request sizes so the mix is reproducible from --seed.
    sizes = [rng.randint(1, max_request) for _ in range(requests)]
    results: list[tuple[int, float]] = []
    lock = threading.Lock()
    cursor = [0]

    def worker(wid: int) -> None:
        wrng = random.Random(seed * 1000 + wid)
        while True:
            with lock:
                i = cursor[0]
                if i >= requests:
                    return
                cursor[0] += 1
            t0 = time.perf_counter()
            status, _body = fetch_json(
                f"{url}/predict", _request_payload(wrng, sizes[i], dtype),
                timeout=timeout_s,
            )
            elapsed = time.perf_counter() - t0
            with lock:
                results.append((status, elapsed))

    threads = [
        threading.Thread(target=worker, args=(w,)) for w in range(concurrency)
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return {
        "results": results, "wall_s": wall, "sizes": sizes,
        "mode": "closed-loop", "dtype": dtype,
    }


def summarize(raw: dict, before: dict, after: dict) -> dict:
    from pytorch_mnist_ddp_tpu.serving.metrics import percentile

    results = raw["results"]
    ok = sorted(lat for status, lat in results if status == 200)
    by_status: dict[str, int] = {}
    for status, _ in results:
        by_status[str(status)] = by_status.get(str(status), 0) + 1
    compiles_before = before.get("compiles")
    compiles_after = after.get("compiles")
    additional = (
        compiles_after - compiles_before
        if compiles_before is not None and compiles_after is not None
        else None
    )
    return {
        "mode": raw.get("mode", "closed-loop"),
        "dtype": raw.get("dtype", "f32"),
        "offered_rate_rps": raw.get("offered_rate_rps"),
        "achieved_arrival_rate_rps": raw.get("achieved_arrival_rate_rps"),
        "requests": len(results),
        "request_size_range": [min(raw["sizes"]), max(raw["sizes"])],
        "wall_s": raw["wall_s"],
        # throughput_rps keeps its historical meaning (useful 200s per
        # wall second — cross-revision BENCH comparability); goodput_rps
        # is its canonical name going forward, and answered_rps is the
        # shed-inclusive rate — under shedding load the answered/goodput
        # gap is the capacity signal a dtype A/B compares.
        "throughput_rps": len(ok) / raw["wall_s"] if raw["wall_s"] else 0.0,
        "goodput_rps": len(ok) / raw["wall_s"] if raw["wall_s"] else 0.0,
        "answered_rps": len(results) / raw["wall_s"] if raw["wall_s"] else 0.0,
        "server_dtype_latency": after.get("dtypes"),
        "status_counts": by_status,
        "rejected": by_status.get("503", 0),
        "timed_out": by_status.get("504", 0),
        "latency_ms": {
            "p50": 1e3 * percentile(ok, 50),
            "p95": 1e3 * percentile(ok, 95),
            "p99": 1e3 * percentile(ok, 99),
            "mean": 1e3 * sum(ok) / len(ok) if ok else 0.0,
        },
        "server_replicas": after.get("replicas"),
        "server_batch_occupancy_pct": after.get("batch_occupancy_pct"),
        "server_padding_waste_pct": after.get("padding_waste_pct"),
        "server_queue_depth_final": after.get("queue_depth"),
        "server_pipeline": after.get("pipeline"),
        "compiles_before": compiles_before,
        "compiles_after": compiles_after,
        "additional_compiles": additional,
        "server_metrics_before": before,
        "server_metrics_after": after,
    }


def _spin_self_serve(args, replicas: int | None):
    """Start the in-process stack (single engine, or an N-replica pool
    behind the router when ``replicas``), warmed and parity-gated.
    Returns ``(server, sink, url)``; the caller owns teardown."""
    from pytorch_mnist_ddp_tpu.obs.events import open_sink
    from pytorch_mnist_ddp_tpu.serving import InferenceEngine, ServingMetrics
    from pytorch_mnist_ddp_tpu.serving.server import make_server

    metrics = ServingMetrics()
    buckets = [int(b) for b in args.buckets.split(",")]
    dtypes = [args.dtype] if args.dtype != "f32" else None
    batcher_kwargs = dict(
        linger_ms=args.linger_ms, queue_depth=args.queue_depth,
        timeout_ms=args.timeout_ms, max_inflight=args.max_inflight,
        adaptive_linger=not args.no_adaptive_linger,
    )
    sink = open_sink(args.telemetry_dir)
    if replicas is not None:
        from pytorch_mnist_ddp_tpu.serving import EnginePool

        # Same convention as the serving CLI: 0 = one replica per
        # visible device (the EnginePool default).
        pool = EnginePool.from_seed(
            replicas=replicas or None, buckets=buckets, metrics=metrics,
            dtypes=dtypes, aot_cache=args.aot_cache,
        )
        print(
            f"self-serve pool: warming buckets {list(pool.buckets)} x "
            f"dtypes {list(pool.dtypes)} x {pool.n_replicas} replicas"
        )
        pool.warmup(sink=sink)
        if args.dtype != "f32":
            pool.verify_parity(raise_on_failure=True)
        router = pool.start(
            router_policy=args.router_policy, sink=sink, **batcher_kwargs
        )
        server = make_server(pool, metrics, port=0, batcher=router)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        print(
            f"self-serve pool: {url} ({pool.n_replicas} replicas, "
            f"router policy {args.router_policy})"
        )
        return server, sink, url
    engine = InferenceEngine.from_seed(
        buckets=buckets, metrics=metrics, dtypes=dtypes,
        aot_cache=args.aot_cache,
    )
    print(
        f"self-serve: warming buckets {list(engine.buckets)} x dtypes "
        f"{list(engine.dtypes)}"
    )
    engine.warmup()
    if args.dtype != "f32":
        # The variant must clear its parity gate before a single
        # request routes to it (the refusal contract): fail the
        # A/B loudly rather than measure an unverified path.
        gate = engine.verify_parity(raise_on_failure=True)[args.dtype]
        print(
            f"parity gate [{args.dtype}]: PASS "
            f"(max|dlogit| {gate['max_abs_logit_diff']:.2e} <= "
            f"{gate['tolerance']:g}, argmax identical)"
        )
    server = make_server(engine, metrics, port=0, sink=sink, **batcher_kwargs)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    print(
        f"self-serve: {url} (in-flight window {args.max_inflight}, "
        f"adaptive linger {'off' if args.no_adaptive_linger else 'on'})"
    )
    return server, sink, url


def _teardown_self_serve(server, sink) -> None:
    if server is not None:
        server.shutdown()
        server.batcher.stop(drain=True)
        server.server_close()
    if sink is not None:
        sink.close()


def _drive(args, url: str) -> dict:
    """Fire the configured workload (open or closed loop) at ``url``."""
    if args.open_loop:
        print(
            f"driving {args.requests} open-loop Poisson arrivals of "
            f"1..{args.max_request} samples at {args.rate:.0f} req/s"
        )
        return run_open_loop(
            url, args.requests, args.rate, args.max_request,
            args.seed, args.timeout_s,
            max_workers=args.concurrency,
            dtype=args.dtype,
        )
    print(
        f"driving {args.requests} requests of 1..{args.max_request} "
        f"samples at concurrency {args.concurrency}"
    )
    return run_load(
        url, args.requests, args.concurrency, args.max_request,
        args.seed, args.timeout_s, dtype=args.dtype,
    )


def run_replica_sweep(args) -> int:
    """The scale-out A/B: the SAME workload against self-serve pools of
    increasing replica counts, reporting goodput and p99 per rung plus
    scaling efficiency (goodput_N / (N x goodput_1)) —
    ``BENCH_serving_scaleout.json``."""
    counts = [int(c) for c in args.replicas_sweep.split(",")]
    if any(c < 1 for c in counts):
        raise SystemExit("--replicas-sweep counts must be >= 1")
    rows = []
    rc = 0
    for i, n in enumerate(counts):
        server, sink, url = _spin_self_serve(args, replicas=n)
        try:
            _status, before = fetch_json(f"{url}/metrics")
            raw = _drive(args, url)
            _status, after = fetch_json(f"{url}/metrics")
            if args.prom_dump and i == len(counts) - 1:
                with open(args.prom_dump, "w") as f:
                    f.write(fetch_text(f"{url}/metrics?format=prom"))
                print(f"prometheus exposition ({n} replicas): {args.prom_dump}")
        finally:
            _teardown_self_serve(server, sink)
        report = summarize(raw, before, after)
        extra = report["additional_compiles"]
        if extra and not args.no_check_compiles:
            print(f"RETRACE at {n} replicas: {extra} additional compile(s)")
            rc = 1
        rows.append({
            "replicas": n,
            "goodput_rps": report["goodput_rps"],
            "answered_rps": report["answered_rps"],
            "p50_ms": report["latency_ms"]["p50"],
            "p99_ms": report["latency_ms"]["p99"],
            "rejected": report["rejected"],
            "timed_out": report["timed_out"],
            "additional_compiles": extra,
            "router_policy": args.router_policy,
        })
    # Both ratios promise a 1-replica baseline; a sweep that starts at
    # some other rung (e.g. --replicas-sweep 2,4) has no such baseline,
    # so they stay None rather than quietly rebasing.
    base = rows[0]["goodput_rps"] if rows[0]["replicas"] == 1 else None
    for row in rows:
        row["speedup_vs_1"] = (
            row["goodput_rps"] / base if base else None
        )
        row["scaling_efficiency"] = (
            row["goodput_rps"] / (row["replicas"] * base)
            if base else None
        )
    sweep_report = {
        "mode": "open-loop" if args.open_loop else "closed-loop",
        "router_policy": args.router_policy,
        "requests": args.requests,
        "max_request": args.max_request,
        "buckets": [int(b) for b in args.buckets.split(",")],
        "offered_rate_rps": args.rate if args.open_loop else None,
        "sweep": rows,
    }
    with open(args.scaleout_report, "w") as f:
        json.dump(sweep_report, f, indent=2)
    print(f"scale-out report: {args.scaleout_report}")
    for row in rows:
        eff = row["scaling_efficiency"]
        print(
            f"  {row['replicas']} replica(s): "
            f"{row['goodput_rps']:.1f} goodput req/s, "
            f"p99 {row['p99_ms']:.2f} ms, {row['rejected']} rejected"
            + (f", efficiency {eff:.2f}" if eff is not None else "")
        )
    return rc


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    parser.add_argument(
        "--url", default=None,
        help="serving endpoint (http://host:port); omitted = --self-serve",
    )
    parser.add_argument(
        "--self-serve", action="store_true",
        help="spin up an in-process server on a loopback port (fresh "
        "seed weights; the default when --url is omitted)",
    )
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument(
        "--concurrency", type=int, default=8,
        help="closed-loop client threads; in --open-loop mode, the cap on "
        "simultaneously outstanding requests (size it above rate x "
        "latency — a saturated pool shows up as client-side queueing in "
        "the latency percentiles, which are measured from the scheduled "
        "arrival)",
    )
    parser.add_argument(
        "--open-loop", action="store_true",
        help="Poisson arrivals at --rate req/s, independent of "
        "completions (closed-loop client threads otherwise)",
    )
    parser.add_argument(
        "--rate", type=float, default=200.0,
        help="open-loop offered arrival rate, requests/second",
    )
    parser.add_argument(
        "--max-request", type=int, default=16,
        help="request sizes are drawn uniformly from [1, this]",
    )
    parser.add_argument(
        "--dtype", default="f32", choices=("f32", "bf16", "int8"),
        help="route every request to this serving variant (the /predict "
        "\"dtype\" field) — the reduced-precision A/B knob; in "
        "--self-serve mode the variant is warmed and parity-gated "
        "before the run (docs/SERVING.md)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--timeout-s", type=float, default=30.0)
    parser.add_argument(
        "--buckets", default="8,16,32",
        help="bucket ladder for --self-serve mode",
    )
    parser.add_argument(
        "--linger-ms", type=float, default=2.0,
        help="batcher linger for --self-serve mode",
    )
    parser.add_argument(
        "--queue-depth", type=int, default=64,
        help="admission bound for --self-serve mode",
    )
    parser.add_argument(
        "--timeout-ms", type=float, default=1000.0,
        help="per-request server-side deadline for --self-serve mode; "
        "raise it (with --queue-depth) for no-shed capacity A/Bs where "
        "every request must complete",
    )
    parser.add_argument(
        "--max-inflight", type=int, default=2,
        help="in-flight window for --self-serve mode (1 = serial PR-3 "
        "pipeline, for A/B throughput comparisons)",
    )
    parser.add_argument(
        "--no-adaptive-linger", action="store_true",
        help="pin the linger at --linger-ms in --self-serve mode",
    )
    parser.add_argument(
        "--telemetry-dir", default=None,
        help="--self-serve mode: write serving JSONL telemetry here "
        "(summarize with tools/perf_report.py --telemetry)",
    )
    parser.add_argument(
        "--prom-dump", default=None,
        help="after the run, save the endpoint's Prometheus exposition "
        "(/metrics?format=prom) to this file",
    )
    parser.add_argument(
        "--replicas", type=int, default=None, metavar="N",
        help="--self-serve mode: serve an N-replica per-device engine "
        "pool behind the queue-aware router instead of one engine "
        "(0 = one per visible device, as in the serving CLI; "
        "docs/SERVING.md scale-out)",
    )
    parser.add_argument(
        "--router-policy", default="cost",
        choices=("roundrobin", "least-loaded", "cost"),
        help="replica placement policy for --replicas / --replicas-sweep",
    )
    parser.add_argument(
        "--replicas-sweep", default=None, metavar="N1,N2,...",
        help="scale-out sweep: run the SAME workload against self-serve "
        "pools of each listed replica count and report goodput vs. "
        "replicas at fixed p99 with scaling efficiency "
        "(--scaleout-report; --prom-dump saves the last rung's "
        "exposition)",
    )
    parser.add_argument(
        "--scaleout-report", default="BENCH_serving_scaleout.json",
        help="where --replicas-sweep writes its report",
    )
    parser.add_argument(
        "--aot-cache", default=None, metavar="DIR",
        help="--self-serve mode: shared serialized-executable store for "
        "the engine(s) (compile/aot.ExecutableStore; a warm pool start "
        "deserializes every replica's grid with zero traces)",
    )
    parser.add_argument("--report", default="BENCH_serving.json")
    parser.add_argument(
        "--no-check-compiles", action="store_true",
        help="don't fail when the run triggered additional compiles",
    )
    args = parser.parse_args(argv)

    if args.url and args.replicas is not None:
        # Silently measuring a remote single endpoint while the report
        # claims N replicas is exactly the confusion a benchmark tool
        # must not allow.
        parser.error("--replicas is --self-serve only; a --url endpoint "
                     "chooses its own replica count")
    if args.replicas_sweep:
        if args.url:
            parser.error("--replicas-sweep drives self-serve pools; "
                         "drop --url")
        return run_replica_sweep(args)

    server = None
    sink = None
    if args.url and not args.self_serve:
        url = args.url.rstrip("/")
    else:
        server, sink, url = _spin_self_serve(args, replicas=args.replicas)

    try:
        _status, before = fetch_json(f"{url}/metrics")
        raw = _drive(args, url)
        _status, after = fetch_json(f"{url}/metrics")
        if args.prom_dump:
            with open(args.prom_dump, "w") as f:
                f.write(fetch_text(f"{url}/metrics?format=prom"))
            print(f"prometheus exposition: {args.prom_dump}")
    finally:
        _teardown_self_serve(server, sink)

    report = summarize(raw, before, after)
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)

    lat = report["latency_ms"]
    print(
        f"done in {report['wall_s']:.2f}s ({report['mode']}"
        + (f", dtype {report['dtype']}" if report["dtype"] != "f32" else "")
        + (f", offered {report['offered_rate_rps']:.0f} req/s"
           if report["offered_rate_rps"] else "")
        + "): "
        f"{report['throughput_rps']:.1f} req/s, "
        f"p50 {lat['p50']:.2f} ms / p95 {lat['p95']:.2f} ms / "
        f"p99 {lat['p99']:.2f} ms, "
        f"{report['rejected']} rejected (503), "
        f"occupancy {report['server_batch_occupancy_pct']:.1f}%"
        if report["server_batch_occupancy_pct"] is not None
        else "done (no server occupancy reported)"
    )
    print(f"report: {args.report}")
    extra = report["additional_compiles"]
    if extra is None:
        print("warning: endpoint reports no compile gauge; retrace check skipped")
    elif extra > 0:
        print(
            f"RETRACE: {extra} additional compile(s) during the run — "
            "request shapes escaped the bucket policy"
        )
        if not args.no_check_compiles:
            return 1
    else:
        print("zero additional compiles (bucket firewall held)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
