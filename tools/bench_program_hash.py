"""Print a SHA-256 of the benchmark program's StableHLO.

The driver's round-end ``python bench.py`` must hit a warm persistent XLA
cache (``~/.cache/tpu_mnist_ddp/xla``) or it pays the ~19 s one-time
compile inside the recorded wall clock.  Cache entries key on the compiled
program, so any commit that changes the fused run's HLO silently
invalidates them (round-1 postmortem: a last-minute RNG flip did exactly
that).

This tool makes the check cheap without TPU access: StableHLO lowering is
platform-independent at this level, so if the hash printed here matches
the hash at the commit that last warmed the cache, the TPU cache is still
valid.  The tool hashes the tree it is RUN FROM (``os.getcwd()`` leads the
import path), so compare across commits with::

    python tools/bench_program_hash.py           # current working tree
    git worktree add /tmp/old <commit>
    cp tools/bench_program_hash.py /tmp/old/tools/  # if absent there
    (cd /tmp/old && python tools/bench_program_hash.py)

The protocol (batch/eval sizes, epochs, PRNG) is imported from
``bench.PROTOCOL`` — the single source bench.py's own defaults use — so
the hashed program cannot drift from the one the benchmark compiles.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import sys


def main() -> None:
    # --num-devices must match the bench topology: bench.py builds its mesh
    # from all visible devices, and a different mesh lowers different
    # StableHLO.  Default 1 = this host's single tunneled chip; on a
    # multi-chip host pass the chip count or the warm-cache check can
    # false-pass/false-fail (round-2 advisor finding).
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-devices", type=int, default=1)
    opts = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if opts.num_devices > 1:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={opts.num_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.getcwd())
    from bench import PROTOCOL, TEST_SET_SIZE, TRAIN_SET_SIZE

    jax.config.update("jax_default_prng_impl", PROTOCOL["prng_impl"])
    import jax.numpy as jnp

    from pytorch_mnist_ddp_tpu.parallel.fused import make_fused_run
    from pytorch_mnist_ddp_tpu.parallel.mesh import make_mesh

    n = opts.num_devices
    mesh = make_mesh(num_data=n, devices=jax.devices()[:n])
    run_fn, _ = make_fused_run(
        mesh, TRAIN_SET_SIZE, TEST_SET_SIZE,
        global_batch=PROTOCOL["batch_size"],
        eval_batch=PROTOCOL["test_batch_size"],
        epochs=PROTOCOL["epochs"],
        from_key=True,
    )
    key = jax.random.PRNGKey(1)
    args = (
        key,
        jnp.zeros((TRAIN_SET_SIZE, 28, 28), jnp.uint8),
        jnp.zeros((TRAIN_SET_SIZE,), jnp.int32),
        jnp.zeros((TEST_SET_SIZE, 28, 28), jnp.uint8),
        jnp.zeros((TEST_SET_SIZE,), jnp.int32),
        key,
        key,
        jnp.ones((PROTOCOL["epochs"],), jnp.float32),
    )
    text = run_fn.lower(*args).as_text()
    print(hashlib.sha256(text.encode()).hexdigest())


if __name__ == "__main__":
    main()
