"""Micro-benchmark: dense attention vs the Pallas flash kernel on TPU.

Times N forward (and optionally forward+backward) passes of
``ops/attention.py:full_attention`` against
``ops/pallas_attention.py:flash_attention`` at long-context shapes —
where the fused kernel's O(t) HBM footprint vs dense's materialized
[b, h, t, t] score tensor is the design point.  Each variant is one
jitted ``lax.scan`` over the iterations (dispatch-free comparison, the
tools/pallas_opt_bench.py harness shape), timed after a warmup, with a
D2H read inside the window (block_until_ready can return early through
the remote tunnel).  Prints one JSON line per shape with microseconds
per call and the HBM bytes the dense path materializes for scores.

Run on real TPU (a tunnel window); CPU+interpret only with --allow-cpu.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_mnist_ddp_tpu.utils.jax_compat import shard_map  # noqa: E402

# (batch, tokens, heads, head_dim): the ViT's own tiny geometry, then
# long-context shapes where flash is the point (at t=8192 the dense
# path materializes a 512 MB f32 score tensor; flash keeps O(t)).
SHAPES = [(8, 16, 4, 16), (4, 512, 4, 64), (2, 2048, 4, 64),
          (1, 8192, 2, 64)]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--grad", action="store_true",
                    help="also time forward+backward")
    ap.add_argument("--parity", action="store_true",
                    help="also record COMPILED-MODE parity vs the dense "
                         "oracle at each shape (fwd + grad max |err|, f32 "
                         "and bf16) — the on-hardware counterpart of the "
                         "interpret-mode tests/test_flash.py suite")
    ap.add_argument("--allow-cpu", action="store_true")
    ap.add_argument("--budget-s", type=float, default=480.0,
                    help="soft time budget: once exceeded, remaining "
                         "SHAPES are skipped (recorded as skipped rows) "
                         "so an outer timeout can never discard the "
                         "already-measured rows with the whole process")
    opts = ap.parse_args()
    t_start = time.perf_counter()

    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    if backend != "tpu" and not opts.allow_cpu:
        print(json.dumps({"error": f"backend {backend!r}; pass --allow-cpu "
                          "to run interpret-mode sanity timings"}))
        return 1
    if backend != "tpu":
        os.environ["TPU_MNIST_PALLAS_INTERPRET"] = "1"

    def timed(fn, q, k, v, out_to_q=lambda r: r) -> float:
        """Per-call microseconds over a jitted scan whose carry feeds each
        call's output back as the next query — the iteration dependence
        that defeats loop-invariant hoisting, and traced (not closure-
        captured) operands so nothing constant-folds at compile time.
        ``out_to_q`` projects fn's result to a q-shaped carry (identity
        for the forward; dq for the grad variant)."""

        def run(q0, k0, v0):
            def body(qc, _):
                return out_to_q(fn(qc, k0, v0)), ()

            final, _ = jax.lax.scan(body, q0, None, length=opts.iters)
            return final

        jit_run = jax.jit(run)
        out = jit_run(q, k, v)  # warmup: trace + compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = jit_run(q, k, v)
        float(jax.tree.leaves(out)[0].ravel()[0])  # D2H inside the window
        return (time.perf_counter() - t0) / opts.iters * 1e6

    rows = []
    for b, t, h, d in SHAPES:
        if time.perf_counter() - t_start > opts.budget_s:
            rows.append({"shape": [b, t, h, d],
                         "skipped": f"over --budget-s {opts.budget_s}"})
            continue
        # Per-shape failure isolation (round-4 advisor): an OOM or compile
        # failure at one shape (the big ones materialize ~0.5 GB dense
        # scores; grad triples that) must not discard the rows already
        # measured in this window — record an error row and move on.  Each
        # finished row is also echoed to stderr immediately, so even a
        # SIGKILL mid-ladder leaves the measurements in the .err sidecar.
        try:
            row = _bench_shape(opts, timed, (b, t, h, d))
        except Exception as e:
            row = {"shape": [b, t, h, d], "error": repr(e)[:300]}
        print(f"row: {json.dumps(row)}", file=sys.stderr, flush=True)
        rows.append(row)

    ring_smoke = _ring_smoke()
    _emit(opts, rows, ring_smoke, backend)
    return 0


def _bench_shape(opts, timed, shape_tuple):
    import jax
    import jax.numpy as jnp

    from pytorch_mnist_ddp_tpu.ops.attention import full_attention
    from pytorch_mnist_ddp_tpu.ops.pallas_attention import flash_attention

    b, t, h, d = shape_tuple
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, t, h, d)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    row = {
        "shape": list(shape),
        "dense_scores_mb": round(b * h * t * t * 4 / 2**20, 1),
        "dense_us": round(timed(full_attention, q, k, v), 2),
        "flash_us": round(timed(flash_attention, q, k, v), 2),
    }
    if opts.grad:
        def dense_loss(q, k, v):
            return (full_attention(q, k, v) ** 2).sum()

        def flash_loss(q, k, v):
            return (flash_attention(q, k, v) ** 2).sum()

        # Feed dq back as the next q, RMS-normalized so 50 chained
        # grad calls can't decay/overflow the operands (the normalize
        # is negligible next to the attention FLOPs).
        def dq_carry(r):
            dq = r[0]
            rms = jnp.sqrt(jnp.mean(dq.astype(jnp.float32) ** 2) + 1e-12)
            return (dq / rms).astype(dq.dtype)

        row["dense_grad_us"] = round(
            timed(jax.grad(dense_loss, argnums=(0, 1, 2)), q, k, v,
                  out_to_q=dq_carry), 2
        )
        row["flash_grad_us"] = round(
            timed(jax.grad(flash_loss, argnums=(0, 1, 2)), q, k, v,
                  out_to_q=dq_carry), 2
        )
    if opts.parity:
        # Non-interpret parity vs the dense oracle, the check the
        # interpret-mode test suite cannot provide (round-3 verdict
        # item 2).  Tolerances mirror tests/test_flash.py.
        def max_err(a, b):
            return float(jnp.abs(
                a.astype(jnp.float32) - b.astype(jnp.float32)
            ).max())

        def dense_l(q, k, v):
            return (full_attention(q, k, v).astype(jnp.float32) ** 2).sum()

        def flash_l(q, k, v):
            return (flash_attention(q, k, v).astype(jnp.float32) ** 2).sum()

        parity = {}
        for label, dt, tol_f, tol_g in (
            ("f32", jnp.float32, 1e-4, 1e-3),
            ("bf16", jnp.bfloat16, 2e-2, 1e-1),
        ):
            qd, kd, vd = (a.astype(dt) for a in (q, k, v))
            fwd_err = max_err(
                jax.jit(flash_attention)(qd, kd, vd),  # jaxlint: disable=JL004 -- 2-dtype parity sweep, one deliberate compile per dtype
                jax.jit(full_attention)(qd, kd, vd),  # jaxlint: disable=JL004 -- 2-dtype parity sweep, one deliberate compile per dtype
            )
            gf = jax.jit(jax.grad(flash_l, argnums=(0, 1, 2)))(qd, kd, vd)  # jaxlint: disable=JL004 -- 2-dtype parity sweep, one deliberate compile per dtype
            gd = jax.jit(jax.grad(dense_l, argnums=(0, 1, 2)))(qd, kd, vd)  # jaxlint: disable=JL004 -- 2-dtype parity sweep, one deliberate compile per dtype
            grad_err = max(max_err(a, b) for a, b in zip(gf, gd))
            parity[label] = {
                "fwd_max_err": fwd_err,
                "grad_max_err": grad_err,
                "ok": bool(fwd_err < tol_f and grad_err < tol_g),
            }
        row["parity"] = parity
    return row


def _ring_smoke():
    # Ring-kernel smoke: flash_block_update under a VMA-tracking
    # shard_map on the real chip (a 1x1 mesh degenerates the ring to the
    # resident fold) — the CPU tests route this path to the pure-JAX twin,
    # so hardware is the only place the kernel-under-VMA trace runs.
    import jax
    import jax.numpy as jnp

    ring_smoke = None
    try:
        from jax.sharding import PartitionSpec as P

        from pytorch_mnist_ddp_tpu.ops.attention import full_attention as fa
        from pytorch_mnist_ddp_tpu.parallel.mesh import DATA_AXIS
        from pytorch_mnist_ddp_tpu.parallel.sp import (
            SEQ_AXIS, make_sp_mesh, ring_attention_flash,
        )

        mesh = make_sp_mesh(num_data=1, num_seq=1, devices=jax.devices()[:1])
        b, t, h, d = 2, 256, 2, 64
        key = jax.random.PRNGKey(1)
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        # Sharded in_specs even on the 1x1 mesh: the inputs must be
        # device-VARYING so the kernel traces with the non-empty vma a
        # real --sp N --flash run produces (replicated P() inputs would
        # smoke a different, trivially-easier trace).
        ring = jax.jit(shard_map(
            lambda q, k, v: ring_attention_flash(q, k, v, SEQ_AXIS),
            mesh=mesh, in_specs=(P(DATA_AXIS, SEQ_AXIS),) * 3,
            out_specs=P(DATA_AXIS, SEQ_AXIS),
        ))
        err = float(jnp.abs(ring(q, k, v) - fa(q, k, v)).max())
        # Same for the OTHER kernel-under-VMA path: the whole-forward
        # kernel through ulysses_attention(use_flash=True) — off-TPU it
        # always routes to the pure twin, so hardware is its only trace.
        from pytorch_mnist_ddp_tpu.parallel.sp import ulysses_attention

        ul = jax.jit(shard_map(
            lambda q, k, v: ulysses_attention(
                q, k, v, SEQ_AXIS, use_flash=True
            ),
            mesh=mesh, in_specs=(P(DATA_AXIS, SEQ_AXIS),) * 3,
            out_specs=P(DATA_AXIS, SEQ_AXIS),
        ))
        ul_err = float(jnp.abs(ul(q, k, v) - fa(q, k, v)).max())
        ring_smoke = {
            "ok": bool(err < 1e-4 and ul_err < 1e-4),
            "ring_max_err": err,
            "ulysses_flash_max_err": ul_err,
        }
    except Exception as e:  # noqa: BLE001 — recorded, not fatal
        ring_smoke = {"ok": False, "error": repr(e)[:300]}
    return ring_smoke


def _emit(opts, rows, ring_smoke, backend):
    import jax

    print(json.dumps({
        "metric": "attention_call_us",
        "iters": opts.iters,
        "backend": backend,
        "device_kind": jax.devices()[0].device_kind,
        "ring_vma_smoke": ring_smoke,
        "rows": rows,
    }))


if __name__ == "__main__":
    sys.exit(main())
