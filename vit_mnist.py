"""ViT-family MNIST training CLI — the attention model family's entrypoint.

Beyond-parity surface (the reference has exactly one model, its CNN —
reference mnist.py:11-34); this CLI drives models/vit.py on the same data
pipeline, printed formats, StepLR schedule, and Adadelta optimizer as the
parity CLIs, and exposes the long-context/distributed modes:

  python vit_mnist.py --epochs 5                 # single device
  python vit_mnist.py --sp 4                     # ring-attention sequence
                                                 # parallel over (data, seq)
  python vit_mnist.py --tp 4                     # Megatron head/MLP sharding
                                                 # over (data, model)
  python vit_mnist.py --sp 2 --tp 2              # 3-D (data, seq, model)
  python vit_mnist.py --pp                       # 2-stage block pipeline
  python vit_mnist.py --experts 8                # switch-MoE with expert
                                                 # parallelism (all_to_all)

``--sp`` / ``--tp`` / ``--pp`` / ``--experts`` are library parallel modes
(parallel/sp.py, tp_vit.py, sp3.py, pp_vit.py, ep.py) — all shard over
every visible device; the data axis absorbs what the minor axes don't use.
"""

from __future__ import annotations

import argparse
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native ViT MNIST example")
    p.add_argument("--batch-size", type=int, default=64, metavar="N")
    p.add_argument("--test-batch-size", type=int, default=1000, metavar="N")
    p.add_argument("--epochs", type=int, default=14, metavar="N")
    p.add_argument("--lr", type=float, default=1.0, metavar="LR")
    p.add_argument("--gamma", type=float, default=0.7, metavar="M")
    p.add_argument("--seed", type=int, default=1, metavar="S")
    p.add_argument("--log-interval", type=int, default=10, metavar="N")
    p.add_argument("--no-cuda", "--no-accel", dest="no_accel",
                   action="store_true", default=False)
    p.add_argument("--dry-run", action="store_true", default=False,
                   help="run a single batch per epoch")
    p.add_argument("--data-root", type=str, default="./data")
    p.add_argument("--sp", type=int, default=None, metavar="S",
                   help="sequence-parallel degree: ring attention over an "
                        "S-way seq axis (parallel/sp.py); composes with "
                        "--tp into the 3-D (data, seq, model) step")
    p.add_argument("--sp-impl", type=str, default="ring",
                   choices=("ring", "ulysses"),
                   help="sequence-parallel strategy: 'ring' rotates k/v "
                        "blocks S-1 ppermute hops; 'ulysses' re-shards "
                        "tokens->heads with one all_to_all pair and runs "
                        "dense (or --flash) attention locally "
                        "(needs heads %% S == 0; plain --sp only)")
    p.add_argument("--tp", type=int, default=None, metavar="M",
                   help="tensor-parallel degree: Megatron-style head/MLP "
                        "sharding over an M-way model axis "
                        "(parallel/tp_vit.py); composes with --sp")
    p.add_argument("--allow-degree-1", action="store_true", default=False,
                   help="take the --sp/--tp/--pp parallel code paths even "
                        "at degree 1: the shard_map programs, collectives, "
                        "and kernels compile and run on a 1-wide axis — "
                        "the single-chip hardware smoke for modes whose "
                        "full degree needs more devices than are visible")
    p.add_argument("--pp", action="store_true", default=False,
                   help="pipeline the transformer blocks across 2 stages "
                        "(parallel/pp_vit.py: microbatched ppermute "
                        "schedule); mutually exclusive with --sp/--tp")
    p.add_argument("--pp-microbatches", type=int, default=2, metavar="M",
                   help="microbatches per shard batch in --pp mode")
    p.add_argument("--pp-stages", type=int, default=2, metavar="S",
                   help="pipeline stage count in --pp mode: depth blocks "
                        "split into S nearly-even chunks over an S-wide "
                        "stage axis (needs --depth >= S)")
    p.add_argument("--experts", type=int, default=0, metavar="E",
                   help="switch-MoE with E experts, expert-parallel over "
                        "the data axis (models/moe.py + parallel/ep.py); "
                        "mutually exclusive with --sp/--tp/--pp")
    p.add_argument("--zero", action="store_true", default=False,
                   help="ZeRO-1 data parallelism over every device: batch "
                        "sharded on the data axis, Adadelta state sharded "
                        "1/N (parallel/zero.py); composes with --fused "
                        "(sharded accumulators in the whole-run scan); "
                        "mutually exclusive with --sp/--tp/--pp/--experts")
    p.add_argument("--flash", action="store_true", default=False,
                   help="fused Pallas flash-attention kernel "
                        "(ops/pallas_attention.py) — composes with every "
                        "mode except --pp/--fused: single-device, --zero, "
                        "--sp (ring hops fold in the partial-accumulation "
                        "kernel), --tp (local head-shard attention), 3-D "
                        "--sp --tp, and --experts; falls back to the "
                        "dense path with a warning off-TPU")
    p.add_argument("--depth", type=int, default=2, metavar="N",
                   help="transformer blocks (default: 2)")
    p.add_argument("--dim", type=int, default=64, metavar="D",
                   help="token embedding width (default: 64)")
    p.add_argument("--bf16", action="store_true", default=False,
                   help="bfloat16 activations/matmuls (params, routing, "
                        "attention accumulation, and log_softmax stay fp32)")
    p.add_argument("--remat", action="store_true", default=False,
                   help="rematerialize each transformer block in backward "
                        "(jax.checkpoint): O(1) live block activations "
                        "instead of O(depth), one extra forward — for "
                        "deep/long configurations; single-device, --zero, "
                        "--sp, and --fused paths")
    p.add_argument("--fused", action="store_true", default=False,
                   help="whole-run fusion: HBM-resident dataset, every "
                        "epoch a device-side scan, ONE jitted call for "
                        "the entire run (parallel/fused_vit.py); "
                        "data-parallel only")
    p.add_argument("--pregather", action="store_true", default=False,
                   help="(--fused only) pre-permuted-epoch input path: "
                        "one big gather per epoch + contiguous per-step "
                        "slices (parallel/fused.py pregather; "
                        "bit-identical batches)")
    p.add_argument("--save-model", action="store_true", default=False,
                   help="save the final params to vit_mnist.npz "
                        "(utils.checkpoint.save_params_tree)")
    p.add_argument("--resume", type=str, default=None, metavar="PATH",
                   help="initialize params from a vit_mnist.npz archive "
                        "instead of random init (optimizer starts fresh)")
    p.add_argument("--profile", type=str, default=None, metavar="DIR",
                   help="capture a jax.profiler (XProf/TensorBoard) trace "
                        "of the whole run into DIR (utils/profiling.trace; "
                        "same surface as the CNN CLI)")
    p.add_argument("--step-stats", action="store_true", default=False,
                   help="print per-epoch host-side step-latency summaries "
                        "(per-batch paths; the fused whole-run has no "
                        "per-step host boundary)")
    p.add_argument("--timings-json", type=str, default=None, metavar="PATH",
                   help="(--fused only) write a wall-clock attribution "
                        "JSON to PATH: compile_s / data_s / run_s split "
                        "via an AOT lower+compile, plus accuracies and "
                        "dataset provenance — the same contract bench.py "
                        "records for the CNN (tools/vit_bench.py reads it)")
    p.add_argument("--save-state", type=str, default=None, metavar="PATH",
                   help="save the FULL training state (params, Adadelta "
                        "accumulators, step/epoch counters) at the end — "
                        "a --resume-state continuation is bit-identical "
                        "to an uninterrupted run")
    p.add_argument("--resume-state", type=str, default=None, metavar="PATH",
                   help="continue training from a --save-state archive "
                        "(schedule, shuffle stream, and epoch numbering "
                        "pick up where the save left off); layout-"
                        "portable across --zero/plain runs and with the "
                        "CNN CLI's archive format")
    return p


def resolve_mode_flags(args) -> tuple[bool, bool]:
    """Validate the mode-flag surface and return ``(sp_on, tp_on)``.

    --sp/--tp default to None (off).  A parallel path is taken at
    degree > 1, or at an explicit degree 1 under --allow-degree-1 (the
    single-chip hardware smoke); after this call args.sp/args.tp are
    plain ints and sp_on/tp_on are the branch selectors.  Every invalid
    flag combination raises SystemExit with the message the CLI prints —
    separated from main() so tests can pin the whole truth table
    without subprocesses (tests/test_e2e.py covers the degree>1 modes
    end-to-end)."""
    for name in ("sp", "tp"):
        v = getattr(args, name)
        if v is not None and v < 1:
            raise SystemExit(f"--{name} must be >= 1, got {v}")
    sp_on = args.sp is not None and (args.sp > 1 or args.allow_degree_1)
    tp_on = args.tp is not None and (args.tp > 1 or args.allow_degree_1)
    args.sp = args.sp or 1
    args.tp = args.tp or 1
    if args.experts > 0 and (sp_on or tp_on or args.pp):
        raise SystemExit("--experts is mutually exclusive with --sp/--tp/--pp")
    if args.pp and (sp_on or tp_on):
        raise SystemExit("--pp is mutually exclusive with --sp/--tp")
    if args.zero and (sp_on or tp_on or args.pp or args.experts > 0):
        # (--zero --fused composes: the fused whole-run carries the
        # sharded accumulator slices, parallel/fused_vit.py zero=True.)
        raise SystemExit(
            "--zero is plain data parallelism; drop --sp/--tp/--pp/"
            "--experts"
        )
    if args.sp_impl != "ring" and tp_on:
        raise SystemExit(
            "--sp-impl ulysses is the plain --sp path; the 3-D --sp --tp "
            "composition rides the ring"
        )
    if args.sp_impl != "ring" and not sp_on:
        raise SystemExit(
            "--sp-impl selects the --sp strategy; add --sp N (> 1)"
        )
    if args.pp and args.pp_stages < 2:
        # (--allow-degree-1 does not extend here: the GPipe engine's
        # first/last stage split is structurally >= 2 stages.)
        raise SystemExit(
            f"--pp-stages must be >= 2, got {args.pp_stages}"
        )
    if args.remat and (tp_on or args.pp or args.experts > 0):
        raise SystemExit(
            "--remat rides the single-device/--zero/--sp/--fused paths; "
            "drop --tp/--pp/--experts"
        )
    if args.flash and (args.pp or args.fused):
        raise SystemExit(
            "--flash composes with every mode except the pipeline engine "
            "and the fused whole-run; drop --pp/--fused"
        )
    if args.pregather and not args.fused:
        raise SystemExit("--pregather is the fused input path; add --fused")
    if args.timings_json and not (args.fused and not args.dry_run):
        # The attribution JSON is produced only by the fused AOT split;
        # --dry-run demotes --fused to the per-batch smoke, so exiting 0
        # without writing PATH would read as a missing-timings run to a
        # consumer like tools/vit_bench.py (round-4 advisor).
        raise SystemExit(
            "--timings-json needs the fused whole-run; "
            + ("drop --dry-run" if args.fused else "add --fused")
        )
    if args.fused and (sp_on or tp_on or args.pp or args.experts > 0):
        raise SystemExit(
            "--fused is the data-parallel whole-run; drop --sp/--tp/--pp/"
            "--experts"
        )
    return sp_on, tp_on


def main() -> None:
    args = build_parser().parse_args()
    sp_on, tp_on = resolve_mode_flags(args)

    import jax

    if args.no_accel:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from pytorch_mnist_ddp_tpu.data.loader import DataLoader
    from pytorch_mnist_ddp_tpu.data.mnist import load_mnist_arrays
    from pytorch_mnist_ddp_tpu.models.vit import (
        ViTConfig,
        init_vit_params,
        vit_forward,
    )
    from pytorch_mnist_ddp_tpu.ops.adadelta import adadelta_update
    from pytorch_mnist_ddp_tpu.ops.loss import nll_loss
    from pytorch_mnist_ddp_tpu.ops.schedule import step_lr
    from pytorch_mnist_ddp_tpu.parallel.ddp import (
        make_train_state,
        replicate_params,
    )
    from pytorch_mnist_ddp_tpu.parallel.mesh import make_mesh
    from pytorch_mnist_ddp_tpu.utils.compile_cache import enable_persistent_cache
    from pytorch_mnist_ddp_tpu.utils.logging import (
        test_summary_lines,
        total_time_line,
        train_log_line,
    )

    enable_persistent_cache()
    start = time.time()
    import atexit
    import contextlib

    from pytorch_mnist_ddp_tpu.utils.profiling import StepStats, trace

    profile_region = contextlib.ExitStack()
    profile_region.enter_context(trace(args.profile))
    # Exception safety without re-indenting the whole body: a run that
    # raises (flag-check SystemExit, mid-train error, Ctrl-C) still
    # finalizes the trace at interpreter exit — the failing run is
    # exactly the one worth profiling.  The explicit close() calls on
    # the success paths keep the trace bounded to the run proper
    # (ExitStack.close is idempotent, so the atexit hook then no-ops).
    atexit.register(profile_region.close)

    cfg = ViTConfig(depth=args.depth, dim=args.dim,
                    num_experts=args.experts, bf16=args.bf16,
                    remat=args.remat)
    params = init_vit_params(jax.random.PRNGKey(args.seed), cfg)
    if args.resume:
        from pytorch_mnist_ddp_tpu.utils.checkpoint import load_params_tree

        loaded = load_params_tree(args.resume)

        # Fail fast on architecture mismatch: tree.map raises on structure
        # drift; leaf shapes are checked explicitly.
        def _check(init, got):
            got = np.asarray(got)
            if got.shape != init.shape:
                raise SystemExit(
                    f"--resume checkpoint shape {got.shape} does not match "
                    f"this config's {init.shape}"
                )
            return got.astype(init.dtype)

        params = jax.tree.map(_check, params, loaded)

    # Full-state continuation (--save-state / --resume-state): the whole
    # TrainState travels, the trainer.fit contract (utils/checkpoint.
    # save_train_state) — archives are layout-portable with the CNN CLI.
    epoch0 = 0
    loaded_state = None
    if (args.resume_state or args.save_state) and (
        tp_on or args.pp or args.experts > 0
    ):
        raise SystemExit(
            "--save-state/--resume-state ride the replicated-state paths "
            "(single-device, --zero, --sp, --fused); drop --tp/--pp/"
            "--experts"
        )
    if args.save_state and args.dry_run:
        raise SystemExit(
            "--dry-run trains one batch per epoch; a --save-state archive "
            "from it would misrepresent its epoch count on resume — drop one"
        )
    if args.resume_state:
        if args.resume:
            raise SystemExit(
                "--resume (model-only) and --resume-state (full state) "
                "are mutually exclusive"
            )
        from pytorch_mnist_ddp_tpu.utils.checkpoint import load_train_state

        loaded_state, epoch0 = load_train_state(args.resume_state)

        def _check_state(init, got):
            got = np.asarray(got)
            if got.shape != init.shape:
                raise SystemExit(
                    f"--resume-state param shape {got.shape} does not "
                    f"match this config's {init.shape}"
                )
            return got.astype(init.dtype)

        # Same npz format as the CNN CLI's archives (shared saver/loader)
        # but the ARCHITECTURE must match this config — a mismatched tree
        # (e.g. a CNN archive) fails the shape/structure check here.
        try:
            checked = jax.tree.map(_check_state, params, loaded_state.params)
        except ValueError as e:
            raise SystemExit(
                f"--resume-state {args.resume_state!r} holds a different "
                f"model's parameter tree: {e}"
            ) from None
        loaded_state = loaded_state._replace(params=checked)

    # One definition of "fresh or resumed" for every replicated-state
    # branch; the zero branches' sharded placement is the only divergence —
    # defined ONCE here so the per-batch and fused --zero paths cannot
    # drift (fresh: accumulators built sharded-in-place; resumed: the
    # archive's per-leaf accumulators convert on placement).
    def base_state():
        return (
            make_train_state(params) if loaded_state is None else loaded_state
        )

    def zero_state(mesh):
        from pytorch_mnist_ddp_tpu.parallel.zero import (
            make_zero_train_state,
            shard_zero_state,
        )

        return (
            make_zero_train_state(params, mesh)
            if loaded_state is None
            else shard_zero_state(loaded_state, mesh)
        )

    def save_state_if_asked(state, mesh, zero_mode=False):
        if not args.save_state:
            return
        from pytorch_mnist_ddp_tpu.utils.checkpoint import save_train_state

        st = state
        if zero_mode:
            from pytorch_mnist_ddp_tpu.parallel.zero import zero_opt_to_per_leaf

            # Archives are always per-leaf (portable across --zero/plain).
            st = state._replace(
                opt=zero_opt_to_per_leaf(state.opt, state.params, mesh)
            )
        save_train_state(
            jax.device_get(st), args.save_state, epoch=epoch0 + args.epochs
        )

    # Whole-run fusion: like the CNN CLI, --dry-run (a per-batch smoke
    # semantics) silently falls back to the per-batch path.
    # (fused-vs-mode exclusivity already validated in resolve_mode_flags.)
    fused = args.fused and not args.dry_run
    if fused:
        from pytorch_mnist_ddp_tpu.parallel.fused_vit import (
            device_put_dataset,
            make_fused_vit_run,
        )

        mesh = make_mesh(num_model=1)
        n_shards = mesh.shape["data"]
        if args.zero:
            # ZeRO-1 composed into the whole-run program: flat accumulator
            # shards in the scan carry (fused_vit.py zero=True).
            state = zero_state(mesh)
        else:
            state = replicate_params(base_state(), mesh)
        tr_x, tr_y, tr_src = load_mnist_arrays(
            args.data_root, "train", return_source=True
        )
        te_x, te_y = load_mnist_arrays(args.data_root, "test", download=False)
        _t0 = time.perf_counter()
        tr_dev = device_put_dataset(tr_x, tr_y, mesh)
        te_dev = device_put_dataset(te_x, te_y, mesh)
        _data_dispatch = time.perf_counter() - _t0
        global_batch = args.batch_size * n_shards
        eval_batch = args.test_batch_size * n_shards
        run_fn, num_batches = make_fused_vit_run(
            mesh, cfg, len(tr_x), len(te_x), global_batch, eval_batch,
            args.epochs, start_epoch=epoch0 + 1, pregather=args.pregather,
            zero=args.zero,
        )
        lr_for_epoch = step_lr(args.lr, args.gamma)
        lrs = jnp.asarray(
            [lr_for_epoch(e)
             for e in range(epoch0 + 1, epoch0 + args.epochs + 1)],
            jnp.float32,
        )
        run_inputs = (
            state, *tr_dev, *te_dev, jax.random.PRNGKey(args.seed), lrs
        )
        if args.timings_json:
            # The bench attribution contract (trainer.py fused path /
            # bench.py): AOT lower+compile so a cold ~20 s compile can't
            # masquerade as device time, D2H reads INSIDE the run_s window
            # so tunnel-async dispatch can't park device time in a later
            # print (trainer.py:437-458 documents both hazards).
            import json as _json

            from pytorch_mnist_ddp_tpu.compile import Program

            timings = {"dataset": tr_src}
            _t1 = time.perf_counter()
            program = Program(
                "fused_vit_run", run_fn, example_args=run_inputs
            )
            program.build()
            timings["compile_s"] = time.perf_counter() - _t1
            _t1 = time.perf_counter()
            jax.block_until_ready((tr_dev, te_dev))
            timings["data_s"] = _data_dispatch + time.perf_counter() - _t1
            _t1 = time.perf_counter()
            state, losses, evals = program.call(*run_inputs)
            losses, evals = np.asarray(losses), np.asarray(evals)
            timings["run_s"] = time.perf_counter() - _t1
            timings.update(
                train_size=len(tr_x), test_size=len(te_x),
                epochs=args.epochs, n_shards=n_shards,
                depth=cfg.depth, dim=cfg.dim,
                epoch1_test_accuracy=float(evals[0, 1]) / len(te_x),
                final_test_accuracy=float(evals[-1, 1]) / len(te_x),
            )
            with open(args.timings_json, "w") as f:
                _json.dump(timings, f)
        else:
            state, losses, evals = run_fn(*run_inputs)
            losses, evals = np.asarray(losses), np.asarray(evals)
        for e in range(args.epochs):
            for b in range(0, num_batches, args.log_interval):
                print(train_log_line(
                    epoch0 + e + 1, b * global_batch, len(tr_x), b,
                    num_batches, float(losses[e, b, 0]),
                ))
            print(test_summary_lines(
                float(evals[e, 0]) / len(te_x), int(evals[e, 1]), len(te_x)
            ))
        save_state_if_asked(state, mesh, zero_mode=args.zero)
        if args.save_model:
            from pytorch_mnist_ddp_tpu.utils.checkpoint import save_params_tree

            save_params_tree(
                jax.device_get(state.params), "vit_mnist.npz"
            )
        profile_region.close()
        print(total_time_line(time.time() - start))
        return

    zero_ran = False  # which branch built the state (drives save layout)
    # One gate (and at most one off-TPU fallback warning) for every
    # flash-capable branch below.
    from pytorch_mnist_ddp_tpu.ops.pallas_attention import (
        flash_active_or_warn,
        select_attention,
    )

    use_flash = flash_active_or_warn(args.flash)
    attention_fn = select_attention(use_flash)
    if sp_on and tp_on:
        from pytorch_mnist_ddp_tpu.parallel.sp3 import (
            make_3d_mesh,
            make_sp3_eval_step,
            make_sp3_train_step,
            shard_sp3_state,
        )

        mesh = make_3d_mesh(num_data=None, num_seq=args.sp,
                            num_model=args.tp)
        state = shard_sp3_state(make_train_state(params), mesh, cfg)
        train_step = make_sp3_train_step(mesh, cfg, use_flash=use_flash)
        eval_step = make_sp3_eval_step(mesh, cfg, use_flash=use_flash)
    elif tp_on:
        from pytorch_mnist_ddp_tpu.parallel.tp_vit import (
            make_vit_tp_eval_step,
            make_vit_tp_train_step,
            shard_vit_tp_state,
        )

        mesh = make_mesh(num_data=None, num_model=args.tp)
        state = shard_vit_tp_state(make_train_state(params), mesh, cfg)
        train_step = make_vit_tp_train_step(mesh, cfg, use_flash=use_flash)
        eval_step = make_vit_tp_eval_step(mesh, cfg, use_flash=use_flash)
    elif args.pp:
        from pytorch_mnist_ddp_tpu.parallel.pp_vit import (
            make_vit_eval_step,
            make_vit_pp_train_step,
        )

        mesh = make_mesh(num_data=None, num_model=args.pp_stages)
        state = replicate_params(make_train_state(params), mesh)
        train_step = make_vit_pp_train_step(
            mesh, cfg, num_micro=args.pp_microbatches
        )
        eval_step = make_vit_eval_step(mesh, cfg)
    elif sp_on:
        from pytorch_mnist_ddp_tpu.parallel.sp import (
            make_sp_eval_step,
            make_sp_mesh,
            make_sp_train_step,
        )

        mesh = make_sp_mesh(num_data=None, num_seq=args.sp)
        state = replicate_params(base_state(), mesh)
        train_step = make_sp_train_step(
            mesh, cfg, use_flash=use_flash, impl=args.sp_impl
        )
        eval_step = make_sp_eval_step(
            mesh, cfg, use_flash=use_flash, impl=args.sp_impl
        )
    elif args.experts > 0:
        from pytorch_mnist_ddp_tpu.parallel.ep import (
            make_ep_eval_step,
            make_ep_train_step,
            shard_ep_state,
        )

        mesh = make_mesh(num_model=1)
        state = shard_ep_state(make_train_state(params), mesh, cfg)
        train_step = make_ep_train_step(mesh, cfg, use_flash=use_flash)
        eval_step = make_ep_eval_step(mesh, cfg, use_flash=use_flash)
    elif args.zero:
        from pytorch_mnist_ddp_tpu.parallel.pp_vit import make_vit_eval_step
        from pytorch_mnist_ddp_tpu.parallel.zero import make_zero_vit_train_step

        mesh = make_mesh(num_model=1)
        zero_ran = True
        state = zero_state(mesh)
        train_step = make_zero_vit_train_step(
            mesh, cfg, attention_fn=attention_fn
        )
        eval_step = make_vit_eval_step(mesh, cfg, attention_fn=attention_fn)
    else:

        mesh = make_mesh(num_data=1, devices=jax.devices()[:1])
        state = replicate_params(base_state(), mesh)

        @jax.jit
        def train_step(state, x, y, w, lr):
            def loss_fn(p):
                logp = vit_forward(p, x, cfg, attention_fn=attention_fn)
                return nll_loss(logp, y, w, reduction="mean")

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            p2, opt = adadelta_update(
                state.params, grads, state.opt, lr, 0.9, 1e-6
            )
            return state._replace(
                params=p2, opt=opt, step=state.step + 1
            ), loss[None]

        @jax.jit
        def eval_step(params, x, y, w):
            logp = vit_forward(params, x, cfg, attention_fn=attention_fn)
            loss_sum = nll_loss(logp, y, w, reduction="sum")
            correct = ((jnp.argmax(logp, axis=1) == y) * w).sum()
            return jnp.stack([loss_sum, correct])


    # Every mode evaluates on its (possibly sharded) live params.
    eval_params = lambda s: s.params  # noqa: E731

    tr_x, tr_y = load_mnist_arrays(args.data_root, "train")
    te_x, te_y = load_mnist_arrays(args.data_root, "test", download=False)

    n_shards = mesh.shape["data"]
    global_batch = args.batch_size * n_shards
    train_loader = DataLoader(
        tr_x, tr_y, global_batch, mesh=mesh, shuffle=True, seed=args.seed
    )
    test_loader = DataLoader(
        te_x, te_y, args.test_batch_size * n_shards, mesh=mesh,
        shuffle=False, mask_padding=True,
    )
    lr_for_epoch = step_lr(args.lr, args.gamma)

    for epoch in range(epoch0 + 1, epoch0 + args.epochs + 1):
        lr = jnp.float32(lr_for_epoch(epoch))
        num_batches = len(train_loader)
        stats = StepStats() if args.step_stats else None
        if stats is not None:
            stats.start()
        for batch_idx, (x, y, w) in enumerate(train_loader.epoch(epoch)):
            state, losses = train_step(state, x, y, w, lr)
            if stats is not None:
                stats.mark(losses)
            if batch_idx % args.log_interval == 0:
                local_loss = float(
                    np.asarray(losses.addressable_shards[0].data)[0]
                )
                print(train_log_line(
                    epoch, batch_idx * global_batch, len(tr_x),
                    batch_idx, num_batches, local_loss,
                ))
            if args.dry_run:
                break
        if stats is not None:
            print(stats.summary_line(epoch))
        totals = np.zeros(2)
        for x, y, w in test_loader.epoch(0):
            totals += np.asarray(eval_step(eval_params(state), x, y, w))
            if args.dry_run:
                break
        print(test_summary_lines(
            totals[0] / len(te_x), int(totals[1]), len(te_x)
        ))

    # zero_ran (not args.zero) so the layout conversion tracks the branch
    # that actually built the state, whatever future flag combos allow.
    save_state_if_asked(state, mesh, zero_mode=zero_ran)
    if args.save_model:
        from pytorch_mnist_ddp_tpu.parallel.tp import gather_replicated
        from pytorch_mnist_ddp_tpu.utils.checkpoint import save_params_tree

        # gather_replicated is a no-op reshard for replicated trees and the
        # expert all-gather for EP-sharded stacks.
        host_params = jax.device_get(
            gather_replicated(eval_params(state), mesh)
        )
        save_params_tree(host_params, "vit_mnist.npz")

    profile_region.close()
    print(total_time_line(time.time() - start))


if __name__ == "__main__":
    main()
