// Native data-loader core (replaces the C++ machinery torch's DataLoader
// delegates to — pin-memory staging + worker-side batch collation;
// SURVEY.md N7, reference mnist_ddp.py:146-151).
//
// The hot path of host-side batch assembly is gather + normalize:
//     out[i] = (images[idx[i]] / 255 - mean) / std
// done here in one multithreaded pass into a caller-owned staging buffer
// (written once, handed straight to the device transfer — the role pinned
// memory plays in the reference).  Also provides an IDX header parser so
// dataset loading never round-trips through Python byte-twiddling.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Parse an MNIST IDX header. Returns 0 on success.
//   buf/len:   raw file bytes
//   out_dims:  int64[4] -> {count, rows, cols, payload_offset}
// Images (magic 2051) give rows/cols; labels (magic 2049) give rows=cols=0.
int idx_parse_header(const uint8_t* buf, int64_t len, int64_t* out_dims) {
    if (len < 8) return -1;
    uint32_t magic = (uint32_t(buf[0]) << 24) | (uint32_t(buf[1]) << 16) |
                     (uint32_t(buf[2]) << 8) | uint32_t(buf[3]);
    // Signed 32-bit read (then widened), matching Python's struct ">i":
    // a sign-bit-set count must parse as negative and be rejected by the
    // n < 0 guards below on BOTH parsers, not accepted here as 2^31+.
    auto be32 = [&](int64_t off) {
        uint32_t u = (uint32_t(buf[off]) << 24) | (uint32_t(buf[off + 1]) << 16) |
                     (uint32_t(buf[off + 2]) << 8) | uint32_t(buf[off + 3]);
        return int64_t(int32_t(u));
    };
    if (magic == 2051) {  // images
        if (len < 16) return -1;
        int64_t n = be32(4), rows = be32(8), cols = be32(12);
        if (n < 0 || rows <= 0 || cols <= 0) return -2;
        // Overflow-safe truncation check: n*rows*cols (and even rows*cols)
        // can exceed int64 for hostile headers, so divide instead of
        // multiplying — floor(floor(a/b)/c) == floor(a/(b*c)) for
        // positive b, c.
        if ((len - 16) / rows / cols < n) return -2;
        out_dims[0] = n; out_dims[1] = rows; out_dims[2] = cols; out_dims[3] = 16;
        return 0;
    }
    if (magic == 2049) {  // labels
        int64_t n = be32(4);
        if (n < 0 || len < 8 + n) return -2;
        out_dims[0] = n; out_dims[1] = 0; out_dims[2] = 0; out_dims[3] = 8;
        return 0;
    }
    return -3;
}

// Gather + normalize a batch: for each of n indices, read one pixel_count
// uint8 image and write float32 (x/255 - mean)/std into out (contiguous
// [n, pixel_count]).  Threaded over samples.
void gather_normalize(const uint8_t* images, const int32_t* indices,
                      int64_t n, int64_t pixel_count, float mean, float stddev,
                      float* out) {
    const float scale = 1.0f / (255.0f * stddev);
    const float shift = -mean / stddev;
    auto worker = [&](int64_t begin, int64_t end) {
        for (int64_t i = begin; i < end; ++i) {
            const uint8_t* src = images + int64_t(indices[i]) * pixel_count;
            float* dst = out + i * pixel_count;
            for (int64_t p = 0; p < pixel_count; ++p) {
                dst[p] = float(src[p]) * scale + shift;
            }
        }
    };
    int64_t hw = std::thread::hardware_concurrency();
    int64_t nthreads = hw < 1 ? 1 : (hw > 8 ? 8 : hw);
    if (n < 256 || nthreads == 1) {  // small batches: threading overhead loses
        worker(0, n);
        return;
    }
    std::vector<std::thread> threads;
    int64_t chunk = (n + nthreads - 1) / nthreads;
    for (int64_t t = 0; t < nthreads; ++t) {
        int64_t begin = t * chunk;
        int64_t end = begin + chunk > n ? n : begin + chunk;
        if (begin >= end) break;
        threads.emplace_back(worker, begin, end);
    }
    for (auto& th : threads) th.join();
}

// Gather labels (uint8 -> int32) for a batch of indices.
void gather_labels(const uint8_t* labels, const int32_t* indices, int64_t n,
                   int32_t* out) {
    for (int64_t i = 0; i < n; ++i) out[i] = int32_t(labels[indices[i]]);
}

}  // extern "C"
